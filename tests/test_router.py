"""Replica fleet router: health-aware routing, failover, drain — chaos-tested.

The load-bearing contracts, in order of consequence:

  * FAILOVER IS LATENCY, NEVER CORRECTNESS — the router pins the seed
    before the first dispatch and decode is (seed, position)-keyed, so a
    request re-dispatched after a replica crash/wedge returns tokens
    BIT-IDENTICAL to the undisturbed run (chaos pin: kill a real replica
    mid-decode under concurrent load; 100% of requests still complete).
  * RETRIES CANNOT AMPLIFY AN OUTAGE — the retry budget refills as a
    fraction of recent successes; during a full-fleet outage total
    dispatch attempts stay within `M + initial_budget`, and recovery
    resumes service with no router restart.
  * A ROLLING RESTART IS A ZERO-ERROR EVENT — drain stops new
    admissions, waits out the replica's outstanding rows, then ejects
    it; every in-flight and subsequent request completes.
  * a flapping replica cannot absorb live traffic — the circuit opens on
    an error burst, probes back off exponentially, and recovery goes
    through one half-open trial request.

Stub replicas (scriptable HTTP servers) drive the policy/state-machine
tests with a stubbed router clock but REAL sockets; the chaos pins run
against real toy `ContinuousEngine` replicas behind real `ServingServer`s.
"""

import io
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.obs.logging import StructuredLog
from dalle_pytorch_tpu.obs.tracing import Tracer
from dalle_pytorch_tpu.serving.engine import ContinuousEngine
from dalle_pytorch_tpu.serving.faults import FaultInjector
from dalle_pytorch_tpu.serving.router import (
    FleetRouter,
    RetryBudget,
    RouterServer,
    format_route_header,
    parse_route_header,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


# ------------------------------------------------------------ stub fleet


class _StubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        owner = self.server.owner
        if self.path.startswith("/healthz"):
            code = owner.health_code
            body = json.dumps({"status": owner.health_tier}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def do_POST(self):
        owner = self.server.owner
        length = int(self.headers.get("Content-Length", "0") or 0)
        raw = self.rfile.read(length) if length else b"{}"
        with owner.lock:
            owner.hits += 1
            owner.requests.append({
                "path": self.path,
                "body": json.loads(raw or b"{}"),
                "route": self.headers.get("x-dalle-route"),
                "trace": self.headers.get("x-dalle-trace"),
            })
            behavior = owner.behavior
            delay = owner.delay_s
        if self.path.startswith("/admin/"):
            self._json(200, {"ok": True})
            return
        if delay:
            time.sleep(delay)
        if behavior == "ok":
            body = owner.requests[-1]["body"]
            self._json(200, {
                "tokens": [[int(body.get("seed", 0))] * 4],
                "seed": body.get("seed"),
                "replica": owner.name,
                "route": owner.requests[-1]["route"],
                "trace": owner.requests[-1]["trace"],
                "trace_id": "deadbeefdeadbeef",
            })
        elif behavior == "error":
            self._json(500, {"error": "engine fell over"})
        elif behavior == "busy":
            self._json(
                503, {"error": "queue full"},
                [("Retry-After", str(owner.retry_after))],
            )
        elif behavior == "quota":
            self._json(
                429, {"error": "tenant over quota"},
                [("Retry-After", str(owner.retry_after))],
            )
        elif behavior == "reset":
            raise ConnectionError("stub reset")  # socket dies, no response
        else:
            raise AssertionError(f"unknown behavior {behavior}")

    def _json(self, code, payload, extra=()):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


class _StubServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class StubReplica:
    """Scriptable replica: behavior switchable mid-test, every request
    recorded (the chaos assertions count dispatch attempts here)."""

    def __init__(self, name="stub"):
        self.name = name
        self.behavior = "ok"
        self.delay_s = 0.0
        self.retry_after = 7
        self.health_code = 200
        self.health_tier = "ok"
        self.hits = 0
        self.requests = []
        self.lock = threading.Lock()
        self._httpd = _StubServer(("127.0.0.1", 0), _StubHandler)
        self._httpd.owner = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.02},
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def kill(self):
        """Hard socket kill: nothing listens afterwards (ECONNREFUSED)."""
        self._httpd.shutdown()
        self._httpd.server_close()

    close = kill


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += float(s)


def _wait_for(cond, deadline_s=10.0, interval_s=0.005):
    """Deadline-poll a predicate instead of sleeping a fixed interval —
    the drain tests need "requests are in flight NOW", and a flat
    sleep(0.1) is both flaky under CPU contention (threads not yet
    dispatched) and slack on fast machines."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


def _fleet(n=2, clock=None, **kw):
    stubs = [StubReplica(f"r{i}") for i in range(n)]
    kw.setdefault("probe_interval_s", 0.5)
    router = FleetRouter(
        [f"{s.name}={s.url}" for s in stubs],
        registry=MetricsRegistry(),
        time_fn=clock if clock is not None else time.monotonic,
        **kw,
    )
    return stubs, router


def _route(router, body=None, headers=None):
    raw = json.dumps(body or {"prompt": "x", "seed": 1}).encode()
    status, resp, extra = router.handle_generate(raw, headers or {})
    payload = json.loads(resp) if resp else {}
    return status, payload, dict(extra)


def _counter(registry, name, label=None):
    fam = registry.get(name)
    if fam is None:
        return 0
    if label is not None:
        items = dict(fam.items())
        return int(items[label].value) if label in items else 0
    if hasattr(fam, "items"):
        return int(sum(c.value for _, c in fam.items()))
    return int(fam.value)


# ----------------------------------------------------------- retry budget


class TestRetryBudget:
    def test_refills_on_success_fraction(self):
        b = RetryBudget(ratio=0.5, initial=1.0, cap=2.0)
        assert b.withdraw() and not b.withdraw()
        for _ in range(2):
            b.deposit()
        assert b.balance == 1.0
        assert b.withdraw() and not b.withdraw()

    def test_cap_bounds_banked_credit(self):
        b = RetryBudget(ratio=1.0, initial=0.0, cap=3.0)
        for _ in range(50):
            b.deposit()
        assert b.balance == 3.0

    def test_counters(self):
        b = RetryBudget(ratio=0.0, initial=1.0)
        assert b.withdraw() and not b.withdraw()
        assert b.withdrawn == 1 and b.denied == 1


# ---------------------------------------------------------- header codec


class TestRouteHeader:
    def test_round_trip(self):
        assert parse_route_header(format_route_header("west", 2, True)) == {
            "replica": "west", "attempt": 2, "hedged": True,
        }

    @pytest.mark.parametrize("junk", [
        None, "", "x", "a;b;c", "a;1;2", "a b;1;0", ";;", "a;1", 7,
        "a;99999;0",
    ])
    def test_garbage_rejected(self, junk):
        assert parse_route_header(junk) is None


# -------------------------------------------------------- routing policy


class TestRoutingPolicy:
    def test_idle_fleet_spreads_traffic(self):
        stubs, router = _fleet(2)
        try:
            for i in range(10):
                status, payload, _ = _route(
                    router, {"prompt": "x", "seed": i}
                )
                assert status == 200
            assert stubs[0].hits >= 3 and stubs[1].hits >= 3
        finally:
            for s in stubs:
                s.kill()

    def test_seed_pinned_when_client_sent_none(self):
        stubs, router = _fleet(1)
        try:
            status, payload, _ = _route(router, {"prompt": "x"})
            assert status == 200
            sent = stubs[0].requests[0]["body"]
            assert isinstance(sent["seed"], int)
            assert payload["seed"] == sent["seed"]
        finally:
            stubs[0].kill()

    def test_degraded_replica_serves_high_not_low(self):
        clock = FakeClock()
        stubs, router = _fleet(2, clock=clock)
        try:
            stubs[0].health_tier = "degraded"
            router.probe_once()
            assert router.replicas[0].health == "degraded"
            stubs[1].kill()  # only the degraded replica remains
            # give the breaker a clean slate: mark r1 ejected via probes
            for _ in range(router.eject_after_probe_failures):
                clock.advance(router.probe_interval_s + 0.01)
                router.probe_once()
            assert router.replicas[1].health == "ejected"
            status, payload, _ = _route(
                router, {"prompt": "x", "seed": 1, "priority": "high"}
            )
            assert status == 200 and payload["replica"] == "r0"
            status, payload, _ = _route(
                router, {"prompt": "x", "seed": 2, "priority": "low"}
            )
            assert status == 503  # low may not touch a degraded replica
            assert "Retry-After" in _route(
                router, {"prompt": "x", "seed": 3, "priority": "low"}
            )[2]
        finally:
            stubs[0].kill()

    def test_retry_after_cools_that_class_only(self):
        clock = FakeClock()
        stubs, router = _fleet(2, clock=clock)
        try:
            stubs[0].behavior = "busy"
            stubs[0].retry_after = 30
            # normal request: r0 backpressures -> served by r1
            status, payload, _ = _route(router, {"prompt": "x", "seed": 1})
            assert status == 200 and payload["replica"] == "r1"
            assert _counter(
                router.registry, "dalle_router_failovers_total",
                "backpressure",
            ) == 1
            hits_before = stubs[0].hits
            # r0 now cooled for "normal": next normal goes straight to r1
            status, payload, _ = _route(router, {"prompt": "x", "seed": 2})
            assert status == 200 and payload["replica"] == "r1"
            assert stubs[0].hits == hits_before
            # but "high" is NOT cooled: r0 is tried again (and cools high)
            status, payload, _ = _route(
                router, {"prompt": "x", "seed": 3, "priority": "high"}
            )
            assert status == 200 and payload["replica"] == "r1"
            assert stubs[0].hits == hits_before + 1
            # cooldown expires on the stubbed clock
            stubs[0].behavior = "ok"
            clock.advance(31.0)
            stubs[1].kill()
            status, payload, _ = _route(router, {"prompt": "x", "seed": 4})
            assert status == 200 and payload["replica"] == "r0"
        finally:
            stubs[0].kill()

    def test_tenant_quota_429_passes_through_uncooled(self):
        """A 429 is tenant-scoped: the client sees its own quota error
        (with the replica's Retry-After), the replica is NOT cooled for
        the class, and other tenants keep routing to it."""
        stubs, router = _fleet(2)
        try:
            stubs[0].behavior = "quota"
            stubs[0].retry_after = 9
            status, payload, extra = _route(router, {"prompt": "x", "seed": 1})
            assert status == 429 and extra.get("Retry-After") == "9"
            assert stubs[1].hits == 0, "429 must not fail over"
            with router._lock:
                assert not router.replicas[0].cooldowns, (
                    "tenant quota must not cool the replica for the class"
                )
            # a different (under-quota) tenant's request may still land
            # on r0 once it heals
            stubs[0].behavior = "ok"
            status, _, _ = _route(router, {"prompt": "x", "seed": 2})
            assert status == 200
        finally:
            for s in stubs:
                s.kill()

    def test_bad_request_rejected_without_dispatch(self):
        stubs, router = _fleet(1)
        try:
            status, payload, _ = _route(router, {"prompt": "x", "priority": "vip"})
            assert status == 400
            status, _, _ = router.handle_generate(b"not json", {})
            assert status == 400
            assert stubs[0].hits == 0
        finally:
            stubs[0].kill()

    def test_replica_500_fails_over_exactly_once(self):
        stubs, router = _fleet(2)
        try:
            stubs[0].behavior = "error"
            status, payload, _ = _route(router, {"prompt": "x", "seed": 1})
            assert status == 200  # 500 fails over
            total = stubs[0].hits + stubs[1].hits
            assert total == 2
            assert _counter(
                router.registry, "dalle_router_failovers_total", "status"
            ) == 1
        finally:
            for s in stubs:
                s.kill()


# ------------------------------------------------------ failover + breaker


class TestFailoverAndBreaker:
    def test_transport_failure_fails_over(self):
        stubs, router = _fleet(2)
        try:
            stubs[0].kill()  # hard socket kill: ECONNREFUSED
            ok = 0
            for i in range(4):
                status, payload, _ = _route(
                    router, {"prompt": "x", "seed": i}
                )
                ok += status == 200
            assert ok == 4
            assert _counter(
                router.registry, "dalle_router_failovers_total", "transport"
            ) >= 1
        finally:
            stubs[1].kill()

    def test_error_burst_opens_circuit_and_trial_closes_it(self):
        clock = FakeClock()
        stubs, router = _fleet(
            2, clock=clock, error_min_samples=2, error_rate_threshold=0.5,
        )
        try:
            stubs[0].behavior = "error"
            for i in range(3):
                status, _, _ = _route(router, {"prompt": "x", "seed": i})
                assert status == 200  # r1 carries every request
            assert router.replicas[0].health == "ejected"
            assert router.replicas[0].ejected_reason == "error_rate"
            hits = stubs[0].hits
            for i in range(3):  # ejected: r0 sees NO live traffic
                _route(router, {"prompt": "x", "seed": 10 + i})
            assert stubs[0].hits == hits
            # recovery: replica heals, probe half-opens after the backoff
            stubs[0].behavior = "ok"
            clock.advance(router.replicas[0].probe_backoff_s + 0.01)
            router.probe_once()
            assert router.replicas[0].health == "half_open"
            # the trial request closes the circuit
            for i in range(4):
                status, _, _ = _route(router, {"prompt": "x", "seed": 20 + i})
                assert status == 200
            assert router.replicas[0].health == "healthy"
            assert stubs[0].hits > hits
        finally:
            for s in stubs:
                s.kill()

    def test_failed_trial_reopens_with_deeper_backoff(self):
        clock = FakeClock()
        stubs, router = _fleet(
            2, clock=clock, error_min_samples=2, error_rate_threshold=0.5,
        )
        try:
            stubs[0].behavior = "error"
            for i in range(3):
                _route(router, {"prompt": "x", "seed": i})
            first_backoff = router.replicas[0].probe_backoff_s
            stubs[0].health_tier = "ok"  # healthz lies; dispatches still fail
            clock.advance(first_backoff + 0.01)
            router.probe_once()
            assert router.replicas[0].health == "half_open"
            _route(router, {"prompt": "x", "seed": 9})  # trial fails
            assert router.replicas[0].health == "ejected"
            assert router.replicas[0].ejected_reason == "trial"
            assert router.replicas[0].probe_backoff_s > first_backoff
        finally:
            for s in stubs:
                s.kill()

    def test_probe_failures_eject_and_backoff_caps(self):
        clock = FakeClock()
        stubs, router = _fleet(
            2, clock=clock, probe_backoff_s=1.0, probe_backoff_max_s=4.0,
        )
        try:
            stubs[0].kill()
            for _ in range(router.eject_after_probe_failures):
                clock.advance(router.probe_interval_s + 0.01)
                router.probe_once()
            rep = router.replicas[0]
            assert rep.health == "ejected" and rep.ejected_reason == "probe"
            for _ in range(6):  # ejected probes keep failing: backoff caps
                clock.advance(rep.probe_backoff_s + 0.01)
                router.probe_once()
            assert rep.probe_backoff_s == 4.0
        finally:
            stubs[1].kill()


# ------------------------------------------------------------ tail hedging


class TestHedging:
    def test_hedge_first_wins_and_counts(self):
        stubs, router = _fleet(2, hedge_after_ms=50.0)
        try:
            slow = next(s for s in stubs if s.name == "r0")
            slow.delay_s = 2.0
            t0 = time.monotonic()
            status, payload, extra = _route(
                router, {"prompt": "x", "seed": 5}
            )
            latency = time.monotonic() - t0
            assert status == 200
            assert payload["replica"] == "r1", "hedge's answer must win"
            assert latency < 1.5, "first-wins: no waiting out the slow primary"
            assert _counter(router.registry, "dalle_router_hedges_total") == 1
            assert _counter(
                router.registry, "dalle_router_hedge_wins_total"
            ) == 1
        finally:
            for s in stubs:
                s.kill()

    def test_fast_primary_never_hedges(self):
        stubs, router = _fleet(2, hedge_after_ms=500.0)
        try:
            for i in range(3):
                status, _, _ = _route(router, {"prompt": "x", "seed": i})
                assert status == 200
            assert _counter(router.registry, "dalle_router_hedges_total") == 0
        finally:
            for s in stubs:
                s.kill()


# --------------------------------------------- retry budget: the outage pin


class TestRetryBudgetUnderOutage:
    def test_full_outage_attempts_stay_within_budget_and_recovery(self):
        """The acceptance pin: every replica failing, M requests cost at
        most M + initial_budget dispatch attempts fleet-wide (the budget
        refills only on success, so a dead fleet cannot be hammered),
        every client gets an orderly 5xx, and when the fleet heals the
        SAME router resumes service — no restart, no manual reset."""
        clock = FakeClock()
        stubs, router = _fleet(
            3, clock=clock,
            retry_budget_initial=4.0, retry_budget_ratio=0.25,
            error_min_samples=10_000,  # breaker off: count raw attempts
        )
        try:
            for s in stubs:
                s.behavior = "error"  # FULL outage: nothing succeeds
            M = 15
            statuses = []
            for i in range(M):
                status, _, _ = _route(router, {"prompt": "x", "seed": i})
                statuses.append(status)
            total_attempts = sum(s.hits for s in stubs)
            assert total_attempts <= M + 4, (
                f"retry amplification: {total_attempts} attempts for {M} "
                "requests against a budget of 4"
            )
            assert all(s in (500, 503) for s in statuses), statuses
            assert router.budget.balance < 1.0
            # fleet heals: service resumes through the same router
            for s in stubs:
                s.behavior = "ok"
            for i in range(6):
                status, _, _ = _route(router, {"prompt": "x", "seed": 100 + i})
                assert status == 200
            # successes refilled retry capacity (0.25 x 6 > 1)
            assert router.budget.balance >= 1.0
        finally:
            for s in stubs:
                s.kill()

    def _outage_setup(self):
        clock = FakeClock()
        stubs, router = _fleet(2, clock=clock, retry_budget_initial=2.0)
        for s in stubs:
            s.behavior = "error"
        return clock, stubs, router

    def test_budget_exhausted_is_an_orderly_503(self):
        clock, stubs, router = self._outage_setup()
        try:
            seen = set()
            for i in range(6):
                status, payload, _ = _route(router, {"prompt": "x", "seed": i})
                seen.add(status)
            assert seen <= {500, 503}
        finally:
            for s in stubs:
                s.kill()


# ------------------------------------------------------------ downed fleet


def _stub_everything_ejected(clock, stubs, router):
    for s in stubs:
        s.kill()
    for _ in range(router.eject_after_probe_failures):
        clock.advance(router.probe_interval_s + 0.01)
        router.probe_once()


class TestUnroutable:
    def test_all_ejected_rejects_fast_with_retry_after(self):
        clock = FakeClock()
        stubs, router = _fleet(2, clock=clock)
        _stub_everything_ejected(clock, stubs, router)
        assert all(r.health == "ejected" for r in router.replicas)
        t0 = time.monotonic()
        status, payload, extra = _route(router, {"prompt": "x", "seed": 1})
        assert status == 503 and "Retry-After" in extra
        assert time.monotonic() - t0 < 1.0, "unroutable must fail FAST"
        assert _counter(
            router.registry, "dalle_router_unroutable_total"
        ) == 1
        healthy, detail = router.health()
        assert not healthy and detail["status"] == "unhealthy"


# -------------------------------------------------------------- HTTP layer


def _http(method, port, path, body=None, headers=None, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=(json.dumps(body).encode() if body is not None
              else (b"" if method == "POST" else None)),
        headers={"Content-Type": "application/json", **(headers or {})},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read() or b"{}"), dict(
            resp.headers
        )


class TestRouterHTTP:
    def test_generate_healthz_metrics_debug_and_admin(self):
        stubs, router = _fleet(2)
        server = RouterServer(router, port=0, probes=False).start()
        try:
            port = server.port
            status, payload, headers = _http(
                "POST", port, "/generate", {"prompt": "x", "seed": 3}
            )
            assert status == 200 and payload["tokens"] == [[3, 3, 3, 3]]
            assert headers.get("x-dalle-replica") in ("r0", "r1")

            status, health, _ = _http("GET", port, "/healthz")
            assert status == 200 and health["status"] == "ok"
            assert health["role"] == "router"

            status, detail, _ = _http("GET", port, "/debug/replicas")
            assert {r["name"] for r in detail["replicas"]} == {"r0", "r1"}
            assert "retry_budget" in detail

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode()
            assert "dalle_router_replica_state" in text
            assert "dalle_router_retry_budget" in text

            # admin drain via HTTP, then undrain
            status, d, _ = _http(
                "POST", port, "/admin/drain?replica=r0&wait_s=2"
            )
            assert status == 200 and d["mode"] == "drained"
            for i in range(4):  # r0 out of rotation
                _http("POST", port, "/generate", {"prompt": "x", "seed": i})
            assert all(
                r["body"].get("seed") == 3 for r in stubs[0].requests
            ), "drained replica must see no new traffic"
            status, d, _ = _http("POST", port, "/admin/undrain?replica=r0")
            assert status == 200 and d["mode"] == "active"

            with pytest.raises(urllib.error.HTTPError) as e:
                _http("POST", port, "/admin/drain?replica=nope")
            assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _http("POST", port, "/admin/drain")
            assert e.value.code == 400
        finally:
            server.shutdown()
            for s in stubs:
                s.kill()

    def test_trace_context_parented_and_route_header_stamped(self):
        stubs, router = _fleet(1)
        server = RouterServer(router, port=0, probes=False).start()
        try:
            trace_id = "abcd1234abcd1234"
            _http(
                "POST", server.port, "/generate",
                {"prompt": "x", "seed": 1},
                headers={"x-dalle-trace": f"{trace_id}/client:h:1:0"},
            )
            sent = stubs[0].requests[0]
            # the router ADOPTED the inbound trace id and parented the
            # replica hop into its own dispatch span
            assert sent["trace"].startswith(trace_id + "/")
            parent_uid = sent["trace"].split("/", 1)[1]
            assert parent_uid.startswith(f"{router.site}:")
            # routing decision rides the route header
            assert parse_route_header(sent["route"]) == {
                "replica": "r0", "attempt": 0, "hedged": False,
            }
        finally:
            server.shutdown()
            stubs[0].kill()


# ---------------------------------------- drain under load (stub replicas)


class TestDrainUnderLoad:
    def test_drain_waits_out_inflight_and_routes_around(self):
        stubs, router = _fleet(2)
        try:
            for s in stubs:
                s.delay_s = 0.3
            results = []
            threads = [
                threading.Thread(
                    target=lambda i=i: results.append(
                        _route(router, {"prompt": "x", "seed": i})[0]
                    )
                )
                for i in range(4)
            ]
            for t in threads:
                t.start()
            # requests are in flight on both replicas: each stub counts
            # the hit on arrival, then holds the request for delay_s
            assert _wait_for(
                lambda: stubs[0].hits >= 1 and stubs[1].hits >= 1
            ), "requests never reached both replicas"
            detail = router.drain("r0", wait_s=5.0)
            assert detail["mode"] == "drained"
            assert detail["outstanding_rows"] == 0
            for t in threads:
                t.join(timeout=10)
            assert results == [200, 200, 200, 200], (
                "drain must be a zero-error event"
            )
            hits = stubs[0].hits
            for i in range(3):
                status, _, _ = _route(router, {"prompt": "x", "seed": 10 + i})
                assert status == 200
            assert stubs[0].hits == hits, "drained replica got new traffic"
            router.undrain("r0")
            assert router.replicas[0].mode == "active"
        finally:
            for s in stubs:
                s.kill()

    def test_drain_propagates_to_replica_admin(self):
        stubs, router = _fleet(2)
        try:
            router.drain("r0", wait_s=1.0, propagate=True)
            admin = [
                r for r in stubs[0].requests
                if r["path"].startswith("/admin/drain")
            ]
            assert admin, "propagate=1 must hit the replica's own drain"
            router.undrain("r0", propagate=True)
            assert any(
                r["path"].startswith("/admin/undrain")
                for r in stubs[0].requests
            )
        finally:
            for s in stubs:
                s.kill()


# --------------------------------------- replica-side admin + log stamping


@pytest.fixture(scope="module")
def toy():
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


def _replica_server(toy, log=None, **kw):
    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

    model, params = toy
    eng = ContinuousEngine(
        model=model, variables=params, max_batch=2, chunk_tokens=2,
        prefill_batch=2, registry=MetricsRegistry(),
    )
    eng.tokenizer = ByteTokenizer()
    return eng, ServingServer(
        eng, port=0, request_timeout_s=60, log=log, **kw
    ).start()


class TestReplicaAdminDrain:
    def test_drain_refuses_intake_reversibly(self, toy):
        eng, server = _replica_server(toy)
        try:
            port = server.port
            status, d, _ = _http("POST", port, "/admin/drain")
            assert status == 200 and d["draining"] is True
            # healthz reports draining at 503 (a router pulls it)
            with pytest.raises(urllib.error.HTTPError) as e:
                _http("GET", port, "/healthz")
            assert e.value.code == 503
            assert json.loads(e.value.read())["drain"]["quiesced"] is True
            # new work refused with Retry-After
            with pytest.raises(urllib.error.HTTPError) as e:
                _http("POST", port, "/generate", {"prompt": "x", "seed": 1})
            assert e.value.code == 503
            assert e.value.headers.get("Retry-After") is not None
            # undrain restores service end to end
            status, d, _ = _http("POST", port, "/admin/undrain")
            assert status == 200 and d["draining"] is False
            status, health, _ = _http("GET", port, "/healthz")
            assert status == 200
            status, payload, _ = _http(
                "POST", port, "/generate",
                {"prompt": "red", "seed": 3}, timeout=120,
            )
            assert status == 200 and len(payload["tokens"][0]) == IMG_SEQ
        finally:
            server.shutdown()

    def test_route_header_stamped_into_request_log_and_state_dump(self, toy):
        stream = io.StringIO()
        log = StructuredLog(stream=stream, site="repl-a")
        eng, server = _replica_server(toy, log=log)
        try:
            status, payload, _ = _http(
                "POST", server.port, "/generate",
                {"prompt": "red", "seed": 3},
                headers={"x-dalle-route": format_route_header(
                    "repl-a", 2, True
                )},
                timeout=120,
            )
            assert status == 200
            lines = [
                json.loads(l) for l in stream.getvalue().splitlines()
            ]
            req_lines = [l for l in lines if l.get("event") == "request"]
            assert req_lines, "no request log line written"
            line = req_lines[-1]
            # routing decision attributable per attempt...
            assert line["replica"] == "repl-a"
            assert line["attempt"] == 2 and line["hedged"] is True
            # ...joined against the stable process identity
            assert line["site"] == "repl-a" and "host" in line and "pid" in line
            # /debug/state carries the same identity triple
            status, dump, _ = _http("GET", server.port, "/debug/state")
            assert dump["identity"]["site"] == "repl-a"
            assert {"site", "pid", "host"} <= set(dump["identity"])
            # a malformed route header stamps nothing
            status, payload, _ = _http(
                "POST", server.port, "/generate",
                {"prompt": "red", "seed": 4},
                headers={"x-dalle-route": "garbage;;;"}, timeout=120,
            )
            assert status == 200
            line = [
                json.loads(l) for l in stream.getvalue().splitlines()
                if json.loads(l).get("event") == "request"
            ][-1]
            assert "attempt" not in line
        finally:
            server.shutdown()


# ------------------------------------------------- chaos: real toy replicas


def _post_generate(port, body, timeout=120, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class TestChaosRealReplicas:
    """The acceptance pin: 3 REAL in-process replicas (toy
    ContinuousEngine behind ServingServer), one killed/wedged mid-decode
    under concurrent load — 100% completion, bit-identical tokens for
    re-dispatched requests, zero client-visible errors for a drain."""

    def _fleet(self, toy, n=3, **router_kw):
        servers = []
        for _ in range(n):
            _, server = _replica_server(toy)
            servers.append(server)
        router_kw.setdefault("attempt_timeout_s", 30.0)
        router = FleetRouter(
            [f"r{i}=http://127.0.0.1:{s.port}" for i, s in enumerate(servers)],
            registry=MetricsRegistry(),
            **router_kw,
        )
        front = RouterServer(router, port=0, probes=False).start()
        return servers, router, front

    def test_replica_wedged_mid_decode_all_complete_bit_identical(self, toy):
        """The chaos pin. Reference pass over a healthy 3-replica fleet;
        then one replica's chunk dispatch is wedged (FaultInjector
        stall past the router's attempt timeout — the request is
        mid-decode when the wedge bites) under concurrent open-loop
        load: every request still completes, re-dispatched requests
        return bit-identical tokens, and once the wedged replica is
        hard-killed (socket gone, ECONNREFUSED) the fleet keeps
        serving.

        Deflaked (the PR 12 contention flake): the wedge is an
        EVENT-HELD stall (released in the teardown) instead of a 6s
        sleep, so a contention-stretched run can never see the wedged
        replica come back mid-assertion; and the attempt timeout is 4s
        (toy decode is ~100x faster), so a slow healthy replica under
        CPU contention is not misread as wedged — the budgets no longer
        ride on wall-clock races."""
        servers, router, front = self._fleet(toy, attempt_timeout_s=4.0)
        unwedge = threading.Event()
        try:
            port = front.port
            seeds = [101, 102, 103, 104]
            bodies = [
                {"prompt": "red circle", "seed": s, "timeout_s": 60}
                for s in seeds
            ]
            # reference pass over the healthy fleet (same seeds — decode
            # is (seed, position)-keyed, so these ARE the ground truth)
            refs = {}
            for body in bodies:
                status, payload = _post_generate(port, body)
                assert status == 200
                refs[body["seed"]] = payload["tokens"]

            # wedge replica 0: its next chunk dispatch holds until the
            # test releases it — longer than any attempt timeout by
            # construction — freezing every row it holds MID-DECODE;
            # requests routed there must fail over
            servers[0].engine.faults = FaultInjector().stall_nth(
                "chunk", 1, until=unwedge
            )

            results = {}
            errors = []

            def client(body):
                try:
                    status, payload = _post_generate(port, body)
                    if status != 200:
                        errors.append((body["seed"], status))
                    else:
                        results[body["seed"]] = payload["tokens"]
                except Exception as exc:
                    errors.append((body["seed"], repr(exc)))

            threads = [
                threading.Thread(target=client, args=(b,)) for b in bodies
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, f"chaos run had client-visible errors: {errors}"
            assert set(results) == set(seeds), "not every request completed"
            for seed in seeds:
                np.testing.assert_array_equal(
                    results[seed], refs[seed],
                    err_msg=f"failover changed tokens for seed {seed}",
                )
            # at least one request really did leave the wedged replica
            assert _counter(
                router.registry, "dalle_router_failovers_total", "transport"
            ) >= 1, "no request ever timed out off the wedged replica"

            # escalate: hard socket kill of the wedged replica
            # (ECONNREFUSED from now on) — the fleet must keep serving.
            # Release the wedge first so the worker thread can exit.
            unwedge.set()
            servers[0].shutdown(drain=False)
            for seed in (201, 202):
                status, payload = _post_generate(
                    port, {"prompt": "after the crash", "seed": seed,
                           "timeout_s": 60}
                )
                assert status == 200
        finally:
            unwedge.set()
            front.shutdown()
            for s in servers[1:]:
                s.shutdown()

    def test_replica_dead_before_dispatch_fails_over(self, toy):
        """Crash-kill flavor: the replica is GONE (connection refused)
        when the dispatch happens — failover completes bit-identically
        against the healthy-fleet reference."""
        servers, router, front = self._fleet(toy)
        try:
            port = front.port
            body = {"prompt": "crash", "seed": 555, "timeout_s": 60}
            status, payload = _post_generate(port, body)
            assert status == 200
            ref = payload["tokens"]
            servers[0].shutdown(drain=False)  # corpse
            for _ in range(3):  # every retry lands somewhere alive
                status, payload = _post_generate(port, body)
                assert status == 200
                np.testing.assert_array_equal(payload["tokens"], ref)
        finally:
            front.shutdown()
            for s in servers[1:]:
                s.shutdown()

    def test_drain_during_load_is_zero_error_and_rejoin(self, toy):
        servers, router, front = self._fleet(toy)
        try:
            port = front.port
            seeds = list(range(300, 306))
            statuses = []

            def client(seed):
                status, _ = _post_generate(
                    port, {"prompt": "drain", "seed": seed, "timeout_s": 60}
                )
                statuses.append(status)

            threads = [
                threading.Thread(target=client, args=(s,)) for s in seeds
            ]
            for t in threads:
                t.start()
            # drain only once the burst is actually being served: rows
            # outstanding somewhere, or (fast machines) already finished
            assert _wait_for(
                lambda: len(statuses) > 0
                or sum(r.outstanding_rows for r in router.replicas) > 0,
                deadline_s=30.0,
            ), "burst never reached the fleet"
            detail = router.drain("r1", wait_s=30.0, propagate=True)
            assert detail["mode"] == "drained"
            for t in threads:
                t.join(timeout=120)
            assert statuses == [200] * len(seeds), (
                f"rolling restart leaked errors: {statuses}"
            )
            # the drained replica can restart without anyone noticing:
            # here we just verify it holds no outstanding rows and is out
            # of rotation, then rejoin it
            assert router._find("r1").outstanding_rows == 0
            router.undrain("r1", propagate=True)
            status, _ = _post_generate(
                port, {"prompt": "back", "seed": 999, "timeout_s": 60}
            )
            assert status == 200
        finally:
            front.shutdown()
            for s in servers:
                s.shutdown()


# ------------------------------------------------- router-down bench client


@pytest.mark.slow
def test_serve_cli_router_mode_e2e():
    """`serve.py --router` end to end as a subprocess: readiness line,
    routed /generate, /debug/replicas, clean SIGTERM shutdown."""
    import os
    import re
    import signal as signal_mod
    import subprocess
    import sys
    from pathlib import Path

    stub = StubReplica("r0")
    proc = subprocess.Popen(
        [sys.executable, "serve.py", "--router",
         "--replicas", f"edge=http://127.0.0.1:{stub.port}",
         "--port", "0", "--probe_interval_s", "0.2"],
        cwd=Path(__file__).resolve().parents[1],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        line = ""
        for _ in range(200):
            line = proc.stdout.readline()
            if "[router] listening" in line:
                break
        m = re.search(r"http://127\.0\.0\.1:(\d+)", line)
        assert m, f"no readiness line: {line!r}"
        port = int(m.group(1))
        status, payload, _ = _http(
            "POST", port, "/generate", {"prompt": "x", "seed": 7}
        )
        assert status == 200 and payload["tokens"] == [[7, 7, 7, 7]]
        status, detail, _ = _http("GET", port, "/debug/replicas")
        assert detail["replicas"][0]["name"] == "edge"
        proc.send_signal(signal_mod.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        stub.kill()


@pytest.mark.slow
def test_fleet_bench_schema():
    """`bench_serving --replicas 2` emits one JSON line with the fleet
    schema: healthy vs killed windows, router accounting, and a
    100%-completion chaos headline."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SERVE_DIM": "32", "SERVE_DEPTH": "2", "SERVE_FMAP": "4",
        "SERVE_TEXT_SEQ": "8",
        "SERVE_FLEET_SECONDS": "3", "SERVE_FLEET_SLOTS": "2",
        "SERVE_CHUNK_TOKENS": "4",
    }
    out = subprocess.run(
        [sys.executable, "bench_serving.py", "--mode", "open-loop",
         "--replicas", "2"],
        cwd=Path(__file__).resolve().parents[1],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["bench"] == "serving_fleet"
    assert line["metric"] == "fleet_completion_with_replica_killed"
    for key in ("replicas", "healthy", "killed", "router",
                "killed_replica", "p95_killed_vs_healthy", "value"):
        assert key in line, f"missing {key}"
    for window in (line["healthy"], line["killed"]):
        for k in ("offered", "completed", "errors", "rps",
                  "latency_p50_ms", "latency_p95_ms"):
            assert k in window, f"missing window key {k}"
    router_block = line["router"]
    for k in ("failovers", "hedges", "ejections", "retry_budget",
              "per_replica_share"):
        assert k in router_block, f"missing router key {k}"
    fleet_block = line["fleet"]
    for k in ("goodput_fraction", "suggested_replicas",
              "scrape_generations", "chip_seconds_by_tenant",
              "chip_seconds_total"):
        assert k in fleet_block, f"missing fleet key {k}"
    # the final sweep sees the killed replica: its generation is stale
    assert fleet_block["scrape_generations"]["r0"]["stale"] is True
    # both synthetic tenants got chip-seconds attributed
    tenants = {
        k.split("/")[0] for k in fleet_block["chip_seconds_by_tenant"]
    }
    assert {"tenant-a", "tenant-b"} <= tenants
    assert fleet_block["chip_seconds_total"] > 0
    assert 0.0 <= fleet_block["goodput_fraction"] <= 1.0
    # the chaos claim: killing a replica mid-window loses nothing
    assert line["killed"]["completed"] == line["killed"]["offered"], line
    assert line["value"] == 1.0


class TestRouterDownClient:
    def test_bench_fleet_client_survives_router_down(self):
        """bench_serving's fleet client records a router-down request as
        an error outcome instead of raising out of the load loop."""
        from bench_serving import fleet_request

        # nothing listens on this port (bound then closed)
        import socket as socket_mod

        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        out = fleet_request(
            dead_port, {"prompt": "x", "seed": 1}, timeout=1.0
        )
        assert out["ok"] is False and out["status"] is None
        assert out["error"]
        assert out["latency_s"] >= 0
