import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.ops.rotary import build_dalle_rotary, apply_rotary
from dalle_pytorch_tpu.ops.gumbel import gumbel_softmax
from dalle_pytorch_tpu.ops.sampling import top_k_filter, gumbel_sample
from dalle_pytorch_tpu.ops.masks import (
    causal_mask,
    axial_static_mask,
    conv_like_mask,
    block_sparse_layout,
    block_layout_to_token_mask,
)
from dalle_pytorch_tpu.ops.shift import shift_tokens_dalle
from dalle_pytorch_tpu.ops.attention_core import dense_attention, stable_softmax


class TestRotary:
    def test_shape_and_rotation_norm(self):
        dim_head = 64
        fmap = 4
        text_len = 9  # 8 text + bos
        table = build_dalle_rotary(text_len, fmap, dim_head)
        rot_dim = dim_head // 3
        per_block = 2 * (rot_dim // 2)
        assert table.shape == (text_len + fmap * fmap, 3 * per_block)

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, table.shape[0], dim_head))
        y = apply_rotary(table[None, None], x)
        assert y.shape == x.shape
        # rotation preserves the norm of the rotated channel block
        d = table.shape[-1]
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x[..., :d]), axis=-1),
            np.linalg.norm(np.asarray(y[..., :d]), axis=-1),
            rtol=1e-5,
        )
        # pass-through channels untouched
        np.testing.assert_array_equal(np.asarray(x[..., d:]), np.asarray(y[..., d:]))

    def test_text_image_sentinels_differ(self):
        dim_head = 48
        per_block = 2 * ((dim_head // 3) // 2)
        table = np.asarray(build_dalle_rotary(5, 4, dim_head))
        # all image rows share the same text-block angles (sentinel 8192)
        text_block = table[5:, :per_block]
        assert np.allclose(text_block, text_block[0])
        # text rows share the same axial-block angles (sentinel -10)
        axial_block = table[:5, per_block:]
        assert np.allclose(axial_block, axial_block[0])


class TestGumbel:
    def test_soft_sums_to_one(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 7))
        y = gumbel_softmax(jax.random.PRNGKey(1), logits, tau=0.5, hard=False)
        np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-5)

    @pytest.mark.parametrize("reinmax", [False, True])
    def test_hard_is_one_hot_with_grads(self, reinmax):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 8))

        def f(l):
            y = gumbel_softmax(
                jax.random.PRNGKey(1), l, tau=0.9, hard=True, reinmax=reinmax
            )
            return (y * jnp.arange(8)).sum(), y

        (val, y), grad = jax.value_and_grad(f, has_aux=True)(logits)
        assert np.allclose(np.sort(np.asarray(y), axis=-1)[:, :-1], 0)
        assert np.allclose(np.asarray(y).sum(-1), 1.0)
        assert np.abs(np.asarray(grad)).sum() > 0  # straight-through grads flow


class TestSampling:
    def test_top_k_filter(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0, 0.0, -1.0, 2.5, 0.5, 1.5]])
        out = np.asarray(top_k_filter(logits, thres=0.75))  # keep top 2
        kept = np.isfinite(out[0])
        assert kept.sum() == 2
        assert kept[1] and kept[4]

    def test_gumbel_sample_zero_temp_is_argmax_like(self):
        logits = jnp.asarray([[0.0, 100.0, 0.0]])
        s = gumbel_sample(jax.random.PRNGKey(0), logits, temperature=1.0)
        assert int(s[0]) == 1


class TestMasks:
    def test_axial_row_matches_bruteforce(self):
        fmap, seq_len = 4, 19  # text_len = 4
        m = axial_static_mask(seq_len, fmap, axis=0)
        text_len = seq_len + 1 - fmap * fmap
        assert m[:, :text_len].all()
        for qi in range(fmap * fmap):
            for ki in range(fmap * fmap):
                same_row = qi // fmap == ki // fmap
                assert m[text_len + qi, text_len + ki] == same_row

    def test_axial_col(self):
        fmap, seq_len = 4, 19
        m = axial_static_mask(seq_len, fmap, axis=1)
        text_len = seq_len + 1 - fmap * fmap
        for qi in range(fmap * fmap):
            for ki in range(fmap * fmap):
                same_col = qi % fmap == ki % fmap
                assert m[text_len + qi, text_len + ki] == same_col

    def test_conv_like_neighborhood(self):
        fmap, seq_len, k = 4, 19, 3
        m = conv_like_mask(seq_len, fmap, kernel_size=k)
        text_len = seq_len + 1 - fmap * fmap
        # query at (2, 2): rows 0..2, cols 0..2 reachable (sp=1, window r-2..r)
        q = text_len + 2 * fmap + 2
        allowed = {
            (r, c)
            for r in range(0, 3)
            for c in range(0, 3)
        }
        for r in range(fmap):
            for c in range(fmap):
                assert m[q, text_len + r * fmap + c] == ((r, c) in allowed)

    def test_block_sparse_causal_and_global(self):
        layout = block_sparse_layout(
            64, block=8, num_local_blocks=2, num_random_blocks=1,
            global_block_indices=(0,), causal=True, seed=0,
        )
        assert layout.shape == (8, 8)
        assert not np.triu(layout, 1).any()  # causal at block level
        assert layout[:, 0].all()  # global text block
        assert np.diagonal(layout).all()  # local includes self
        token = block_layout_to_token_mask(layout, 8)
        assert not np.triu(token, 1).any()

    def test_masks_are_causal(self):
        fmap, seq_len = 4, 19
        c = causal_mask(seq_len + 1)
        for m in (
            axial_static_mask(seq_len, fmap, 0) & c,
            conv_like_mask(seq_len, fmap),
        ):
            assert not np.triu(m, 1).any()


class TestShift:
    def test_shift_semantics(self):
        b, d, fmap = 2, 8, 3
        text_len, img_len = 4, 9
        n = text_len + img_len
        x = jax.random.normal(jax.random.PRNGKey(0), (b, n, d))
        y = shift_tokens_dalle(x, text_len, fmap)
        x, y = np.asarray(x), np.asarray(y)
        half, q = d // 2, d // 4
        # text: first position's shifted half is zeros
        assert np.allclose(y[:, 0, :half], 0)
        np.testing.assert_allclose(y[:, 1:text_len, :half], x[:, : text_len - 1, :half])
        np.testing.assert_allclose(y[:, :text_len, half:], x[:, :text_len, half:])
        # image grid: first quarter from one row up, second from one col left
        for r in range(fmap):
            for c in range(fmap):
                i = text_len + r * fmap + c
                if r == 0:
                    assert np.allclose(y[:, i, :q], 0)
                else:
                    np.testing.assert_allclose(y[:, i, :q], x[:, i - fmap, :q])
                if c == 0:
                    assert np.allclose(y[:, i, q : 2 * q], 0)
                else:
                    np.testing.assert_allclose(y[:, i, q : 2 * q], x[:, i - 1, q : 2 * q])
                np.testing.assert_allclose(y[:, i, 2 * q :], x[:, i, 2 * q :])


class TestAttentionCore:
    def test_matches_naive_softmax_attention(self):
        rng = jax.random.PRNGKey(0)
        q, k, v = jax.random.normal(rng, (3, 2, 4, 6, 8))
        mask = jnp.asarray(np.tril(np.ones((6, 6), bool)))[None, None]
        out = dense_attention(q, k, v, mask=mask)

        scores = np.einsum("bhid,bhjd->bhij", np.asarray(q), np.asarray(k)) / np.sqrt(8)
        scores = np.where(np.asarray(mask), scores, -1e30)
        w = np.exp(scores - scores.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        expected = np.einsum("bhij,bhjd->bhid", w, np.asarray(v))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)

    def test_stable_softmax_equals_softmax(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 9)) * 10
        np.testing.assert_allclose(
            np.asarray(stable_softmax(x)), np.asarray(jax.nn.softmax(x)), rtol=1e-5
        )
