"""Serving integration: GenerationEngine semantics + the HTTP service.

Engine tests pin the serving-specific sampler contract (fixed-shape
padding, batch-composition-invariant per-seed RNG, per-row sampling
params). Server tests run the full stack — ThreadingHTTPServer →
MicroBatcher → engine — on localhost: two concurrent POST /generate
coalescing into one padded batch (occupancy > 1 in /metrics), plus the
overload/error paths against a fake engine. The slow-marked test drives
`serve.py` itself against a CLI-trained toy checkpoint.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.models.dvae import DiscreteVAE
from dalle_pytorch_tpu.serving.engine import GenerationEngine, SampleSpec
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP
IMG_PX = 16  # FMAP * 2**num_layers


def _build_engine(batch_shapes=(1, 2, 4), cond_scale=1.0):
    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

    tokenizer = ByteTokenizer()
    vae = DiscreteVAE(
        image_size=IMG_PX, num_layers=2, num_tokens=32,
        codebook_dim=16, hidden_dim=16,
    )
    vae_params = vae.init(
        {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)},
        jnp.zeros((1, IMG_PX, IMG_PX, 3)),
    )["params"]
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=tokenizer.vocab_size, text_seq_len=TEXT_SEQ,
        shift_tokens=False, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return GenerationEngine(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        batch_shapes=batch_shapes, cond_scale=cond_scale,
        tokenizer=tokenizer, registry=MetricsRegistry(),
    )


@pytest.fixture(scope="module")
def engine():
    return _build_engine()


def spec(seed, temperature=1.0, top_k=0.9):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:3] = (5, 6, 7)
    return SampleSpec(ids, seed=seed, temperature=temperature, top_k=top_k)


class TestGenerationEngine:
    def test_shapes_padding_and_stats(self, engine):
        tokens, pixels = engine.generate([spec(0), spec(1)])
        assert tokens.shape == (2, IMG_SEQ) and tokens.dtype == np.int32
        assert (tokens >= 0).all() and (tokens < 32).all()
        assert pixels.shape == (2, IMG_PX, IMG_PX, 3)
        assert pixels.min() >= 0.0 and pixels.max() <= 1.0
        # 2 rows rounded up to the compiled shape 2 -> no padding; 3 rows
        # round up to 4
        before = engine.stats.rows_padded
        t3, _ = engine.generate([spec(2), spec(3), spec(4)])
        assert t3.shape == (3, IMG_SEQ)
        assert engine.stats.rows_padded == before + 1

    def test_pick_shape(self, engine):
        assert engine.pick_shape(1) == 1
        assert engine.pick_shape(2) == 2
        assert engine.pick_shape(3) == 4
        with pytest.raises(AssertionError):
            engine.pick_shape(5)

    def test_seed_determinism_and_variation(self, engine):
        a1, _ = engine.generate([spec(123)])
        a2, _ = engine.generate([spec(123)])
        b, _ = engine.generate([spec(124)])
        np.testing.assert_array_equal(a1, a2)
        assert not np.array_equal(a1, b), "different seeds must differ"

    def test_batch_composition_invariance(self, engine):
        """A request's tokens depend only on its (seed, prompt, params) —
        not on which micro-batch or padding slot it lands in. This is what
        makes dynamic batching transparent to callers."""
        alone, _ = engine.generate([spec(55)])
        batched, _ = engine.generate([spec(99), spec(55), spec(7)])
        np.testing.assert_array_equal(alone[0], batched[1])

    def test_per_row_sampling_params(self, engine):
        """Greedy rows (tiny temperature, keep-1 top-k) are deterministic
        across DIFFERENT seeds while stochastic rows vary — the per-row
        parameters really are per-row inside one batch."""
        greedy = [spec(s, temperature=1e-6, top_k=1.0) for s in (1, 2)]
        hot = [spec(s, temperature=1.0, top_k=0.0) for s in (1, 2)]
        toks, _ = engine.generate(greedy + hot)
        np.testing.assert_array_equal(toks[0], toks[1])
        assert not np.array_equal(toks[2], toks[3])

    def test_warmup_and_compile_counters(self):
        eng = _build_engine(batch_shapes=(1, 2))
        eng.warmup()
        assert eng.stats.compiled_shapes == (1, 2)
        misses = eng.registry.get(
            "dalle_serving_engine_compile_misses_total"
        ).value
        hits_before = eng.registry.get(
            "dalle_serving_engine_compile_hits_total"
        ).value
        eng.generate([spec(0)])
        assert eng.registry.get(
            "dalle_serving_engine_compile_misses_total"
        ).value == misses
        assert eng.registry.get(
            "dalle_serving_engine_compile_hits_total"
        ).value == hits_before + 1

    def test_rerank_without_clip_is_identity(self, engine):
        imgs = np.random.rand(3, IMG_PX, IMG_PX, 3).astype(np.float32)
        out, scores, order = engine.rerank("a prompt", imgs)
        np.testing.assert_array_equal(out, imgs)
        assert (scores == 0).all()
        np.testing.assert_array_equal(order, np.arange(3))

    def test_tokenize(self, engine):
        ids = engine.tokenize("red circle")
        assert ids.shape == (TEXT_SEQ,) and ids.dtype == np.int32
        assert (ids > 0).any()


# ------------------------------------------------------------- HTTP layer


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


def _scrape(metrics_text, name):
    for line in metrics_text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    raise AssertionError(f"metric {name} not found")


class TestServingHTTP:
    def test_concurrent_requests_coalesce(self, engine):
        """The acceptance path: two concurrent POSTs arrive within the
        flush deadline and run as ONE padded batch — visible as a
        batch-occupancy observation > 1 in /metrics."""
        engine.warmup()  # all rungs compiled: request latency ~ms, << deadline
        server = ServingServer(
            engine, port=0, max_delay_ms=500, request_timeout_s=60
        ).start()
        try:
            port = server.port
            occ = engine.registry.get("dalle_serving_batch_occupancy_rows")
            base_batches, base_rows = occ.count, occ.sum

            results = {}

            def client(tag, seed):
                results[tag] = _post(
                    port, {"prompt": "small red circle", "seed": seed}
                )

            threads = [
                threading.Thread(target=client, args=(t, s))
                for t, s in (("a", 11), ("b", 22))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)

            for tag in ("a", "b"):
                status, payload = results[tag]
                assert status == 200
                assert payload["shape"] == [1, IMG_PX, IMG_PX, 3]
                assert len(payload["tokens"]) == 1
                assert len(payload["tokens"][0]) == IMG_SEQ
                assert len(payload["images_png_b64"]) == 1
                import base64

                png = base64.b64decode(payload["images_png_b64"][0])
                assert png[:8] == b"\x89PNG\r\n\x1a\n"
            # both rows flushed in one batch: 1 more batch, 2 more rows
            assert occ.count == base_batches + 1, (
                "two concurrent requests should coalesce into one batch"
            )
            assert occ.sum == base_rows + 2

            # /healthz
            status, body = _get(port, "/healthz")
            health = json.loads(body)
            assert status == 200 and health["status"] == "ok"

            # /metrics: Prometheus text with the advertised instruments
            status, text = _get(port, "/metrics")
            assert status == 200
            assert _scrape(text, "dalle_serving_requests_total") >= 2
            assert _scrape(text, "dalle_serving_images_total") >= 2
            assert _scrape(text, "dalle_serving_queue_depth_rows") == 0
            assert _scrape(text, "dalle_serving_request_latency_seconds_p50") > 0
            assert _scrape(text, "dalle_serving_request_latency_seconds_p95") > 0
            assert "dalle_serving_batch_occupancy_rows_bucket" in text
            assert _scrape(
                text, "dalle_serving_engine_compile_hits_total"
            ) >= 1
        finally:
            server.shutdown()

    def test_seeded_request_reproducible_over_http(self, engine):
        server = ServingServer(
            engine, port=0, max_delay_ms=5, request_timeout_s=60
        ).start()
        try:
            body = {"prompt": "blue square", "seed": 777, "num_images": 2}
            _, p1 = _post(server.port, body)
            _, p2 = _post(server.port, body)
            assert p1["tokens"] == p2["tokens"]
            assert p1["seed"] == 777
        finally:
            server.shutdown()

    def test_bad_requests_rejected(self, engine):
        server = ServingServer(engine, port=0, max_delay_ms=5).start()
        try:
            port = server.port
            for body in (
                {"prompt": ""},
                {"prompt": "x", "num_images": 99},
                {"prompt": "x", "top_k": 7.0},
                {"prompt": "x", "seed": "abc"},
                {"prompt": "x", "seed": [1, 2]},
                {"prompt": "x", "temperature": -1.0},
                {"prompt": "x", "temperature": float("nan")},
                {"prompt": "x", "timeout_s": -1},
                {"prompt": "x", "timeout_s": float("nan")},
                {"prompt": "x", "timeout_s": 1e12},
                {"prompt": "x", "rerank": True},  # no CLIP loaded
                {"nope": 1},
            ):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _post(port, body)
                assert e.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(port, "/nope")
            assert e.value.code == 404
        finally:
            server.shutdown()


class FakeServingEngine:
    """Engine test double with the full surface ServingServer touches."""

    def __init__(self, block_event=None, fail=False, max_batch=4):
        from dalle_pytorch_tpu.serving.engine import EngineStats

        self.max_batch = max_batch
        self.batch_shapes = (max_batch,)
        self.registry = MetricsRegistry()
        self.stats = EngineStats()
        self.clip = None
        self.block_event = block_event
        self.fail = fail

    def tokenize(self, prompt):
        return np.zeros(8, np.int32)

    def generate(self, specs):
        if self.block_event is not None:
            assert self.block_event.wait(10.0)
        if self.fail:
            raise RuntimeError("engine exploded")
        # row i's tokens carry its seed so response pairing is checkable
        toks = np.stack(
            [np.full(4, s.seed, dtype=np.int32) for s in specs]
        )
        return toks, None


class RerankingFakeEngine(FakeServingEngine):
    """Returns pixels and a rerank that REVERSES row order, to pin the
    tokens/images/scores pairing contract of the response payload."""

    def __init__(self):
        super().__init__()
        self.clip = object()  # truthy: server includes clip_scores

    def generate(self, specs):
        toks, _ = super().generate(specs)
        pixels = np.zeros((len(specs), 4, 4, 3), np.float32)
        for i, s in enumerate(specs):
            pixels[i] = (s.seed % 7) / 7.0
        return toks, pixels

    def rerank(self, prompt, images):
        order = np.arange(len(images))[::-1]
        scores = np.arange(len(images), dtype=np.float32)[::-1]
        return images[order], scores, order


class TestServingRerank:
    def test_rerank_keeps_tokens_paired_with_images(self):
        server = ServingServer(
            RerankingFakeEngine(), port=0, max_delay_ms=5
        ).start()
        try:
            _, payload = _post(
                server.port,
                {"prompt": "x", "num_images": 3, "seed": 100, "rerank": True},
            )
            # rows were generated with seeds 100,101,102; reversal means
            # tokens come back 102,101,100 — matching the reordered images
            assert [t[0] for t in payload["tokens"]] == [102, 101, 100]
            assert payload["clip_scores"] == [2.0, 1.0, 0.0]
            assert payload["shape"] == [3, 4, 4, 3]
        finally:
            server.shutdown()


class TestServingOverloadPaths:
    def test_engine_error_returns_500_and_unhealthy(self):
        server = ServingServer(
            FakeServingEngine(fail=True), port=0, max_delay_ms=5
        ).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, {"prompt": "boom"})
            assert e.value.code == 500
            # fail fast is also visible to orchestrators via /healthz
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/healthz")
            assert e.value.code == 503
            assert "engine exploded" in json.loads(e.value.read())["last_error"]
        finally:
            server.shutdown()

    def test_queue_full_returns_503(self):
        gate = threading.Event()
        eng = FakeServingEngine(block_event=gate, max_batch=1)
        server = ServingServer(
            eng, port=0, max_delay_ms=1, max_queue_rows=1,
            request_timeout_s=30,
        ).start()
        try:
            port = server.port
            t1 = threading.Thread(
                target=lambda: _post(port, {"prompt": "a"})
            )
            t1.start()
            time.sleep(0.3)  # t1's request is in the engine, queue empty
            t2 = threading.Thread(
                target=lambda: _post(port, {"prompt": "b"})
            )
            t2.start()
            time.sleep(0.3)  # t2's request fills the 1-row queue
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, {"prompt": "c"})
            assert e.value.code == 503
            assert e.value.headers.get("Retry-After") == "1"
            gate.set()
            t1.join(timeout=10)
            t2.join(timeout=10)
        finally:
            server.shutdown()

    def test_queued_timeout_returns_504(self):
        gate = threading.Event()
        eng = FakeServingEngine(block_event=gate, max_batch=1)
        server = ServingServer(
            eng, port=0, max_delay_ms=1, request_timeout_s=30
        ).start()
        try:
            port = server.port
            t1 = threading.Thread(target=lambda: _post(port, {"prompt": "a"}))
            t1.start()
            time.sleep(0.3)
            # queued behind the blocked batch with a tiny timeout
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, {"prompt": "b", "timeout_s": 0.1})
            assert e.value.code == 504
            gate.set()
            t1.join(timeout=10)
        finally:
            server.shutdown()

    def test_health_recovers_after_transient_engine_error(self):
        eng = FakeServingEngine(fail=True)
        server = ServingServer(eng, port=0, max_delay_ms=5).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, {"prompt": "boom"})
            assert e.value.code == 500
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/healthz")
            assert e.value.code == 503
            eng.fail = False  # transient: the next batch succeeds
            status, _ = _post(server.port, {"prompt": "ok"})
            assert status == 200
            status, body = _get(server.port, "/healthz")
            assert status == 200 and json.loads(body)["status"] == "ok"
        finally:
            server.shutdown()

    def test_health_error_decays_without_traffic(self):
        """A health-gated router pulls traffic on 503, so the error must
        time out on its own — not wait for a successful batch that can
        never come."""
        eng = FakeServingEngine(fail=True)
        server = ServingServer(eng, port=0, max_delay_ms=5).start()
        server.error_window_s = 0.3
        try:
            with pytest.raises(urllib.error.HTTPError):
                _post(server.port, {"prompt": "boom"})
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/healthz")
            assert e.value.code == 503
            time.sleep(0.4)  # no traffic at all; the error window lapses
            status, body = _get(server.port, "/healthz")
            assert status == 200
            # the error is still reported for debugging, just not gating
            assert "engine exploded" in json.loads(body)["last_error"]
        finally:
            server.shutdown()

    def test_serve_forever_after_shutdown_returns(self):
        """A SIGTERM during startup shuts down before the serve loop runs;
        entering it afterwards must be a no-op, not a closed-socket crash."""
        server = ServingServer(FakeServingEngine(), port=0, max_delay_ms=1)
        server.shutdown()
        server.serve_forever()  # returns immediately

    def test_shutdown_before_start_does_not_hang(self):
        """socketserver's shutdown() waits on an event only serve_forever
        sets; a never-started server must still tear down cleanly."""
        server = ServingServer(FakeServingEngine(), port=0, max_delay_ms=1)
        t = threading.Thread(target=server.shutdown, daemon=True)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive(), "shutdown() deadlocked on a never-started server"

    def test_shutdown_drains_inflight(self):
        gate = threading.Event()
        eng = FakeServingEngine(block_event=gate, max_batch=1)
        server = ServingServer(eng, port=0, max_delay_ms=1).start()
        port = server.port
        results = {}

        def client():
            results["r"] = _post(port, {"prompt": "a"})

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.3)
        gate.set()
        server.shutdown(drain=True)
        t.join(timeout=10)
        assert results["r"][0] == 200


@pytest.mark.slow
class TestServeCliEndToEnd:
    def test_serve_cli(self, tmp_path):
        """Train a toy checkpoint via the CLIs, start `serve.py`, POST two
        concurrent requests, assert coalescing + metrics, SIGINT-drain."""
        import signal
        import subprocess
        import sys

        from test_e2e import REPO, run_cli, _tiny_vae_ckpt

        vae_path = _tiny_vae_ckpt(tmp_path)
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:32",
            "--vae_path", str(vae_path),
            "--epochs", "1", "--batch_size", "8",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=32", "--set", "bf16=false",
            "--set", "log_images_freq=0",
            "--set", "debug=true", cwd=tmp_path,
        )
        ckpt = tmp_path / "checkpoints" / "dalle.npz"
        assert ckpt.exists()

        import os

        env = dict(os.environ)
        env["DALLE_TPU_FORCE_PLATFORM"] = "cpu"
        trace_dump = tmp_path / "traces.json"
        proc = subprocess.Popen(
            [
                sys.executable, str(REPO / "serve.py"),
                "--dalle_path", str(ckpt), "--port", "0",
                "--batch_shapes", "1,2", "--max_delay_ms", "500",
                "--trace-dump", str(trace_dump),
            ],
            cwd=tmp_path, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            port = None
            deadline = time.monotonic() + 600
            lines = []
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                lines.append(line)
                if "listening on" in line:
                    port = int(line.split("http://")[1].split()[0].rsplit(":", 1)[1])
                    break
            assert port is not None, f"server never came up:\n{''.join(lines)}"

            results = {}

            def client(tag, seed):
                results[tag] = _post(
                    port, {"prompt": "small red circle", "seed": seed},
                    timeout=120,
                )

            threads = [
                threading.Thread(target=client, args=(t, s))
                for t, s in (("a", 1), ("b", 2))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for tag in ("a", "b"):
                status, payload = results[tag]
                assert status == 200
                assert payload["shape"] == [1, 16, 16, 3]

            status, text = _get(port, "/metrics")
            assert status == 200
            assert _scrape(text, "dalle_serving_requests_total") == 2
            # the two concurrent requests coalesced into one 2-row batch
            assert _scrape(text, "dalle_serving_batches_total") == 1
            assert _scrape(text, "dalle_serving_batch_occupancy_rows_sum") == 2
            status, body = _get(port, "/healthz")
            assert json.loads(body)["status"] == "ok"

            status, body = _get(port, "/debug/traces")
            assert status == 200
            live = json.loads(body)
            assert any(
                e.get("name") == "generate" for e in live["traceEvents"]
            )

            proc.send_signal(signal.SIGINT)
            assert proc.wait(timeout=60) == 0
            # --trace-dump wrote a Perfetto-loadable file on drain
            dumped = json.loads(trace_dump.read_text())
            assert len(dumped["traceEvents"]) >= len(live["traceEvents"])
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
