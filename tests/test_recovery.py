"""Crash-fast recovery: compile cache, supervisor, poison quarantine.

The load-bearing contracts, in order of consequence:

  * A WARM CACHE NEVER LIES AND NEVER CRASHES A BOOT — artifacts are
    keyed by the boot fingerprint (jax version, backend, mesh, model
    config, program ladder); a mismatch is a counted MISS, a
    corrupt/truncated file is a counted REJECT, and both degrade to the
    ordinary cold recompile path. The warm path itself is pinned at the
    compile-guard level: a second compilation of the same HLO against a
    populated persistent cache is a cache HIT, and `tally.uncached`
    stays zero (the slow serve.py e2e pins the same contract across two
    real boots).
  * THE SUPERVISOR'S RESTART POLICY IS A PURE FUNCTION OF THE CLOCK —
    the backoff schedule (capped exponential, streak reset after a
    stable run) and the crash-loop hold-down (N abnormal exits inside
    the window) are pinned deterministically through `_on_exit`; the
    run loop is exercised against scripted child processes.
  * QUARANTINE CATCHES THE CAUSE AND CLEARS THE BYSTANDER — a request
    implicated in exactly K consecutive replica-crash incidents gets a
    terminal 422 with the incident ids (and an identical resubmission
    is refused at ingress), while an innocent request that shared the
    crashed replica survives failover, because one replica death is ONE
    coalesced incident and its own later success absolves it.
"""

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher, MicroBatcher
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.faults import FaultInjector, InjectedFault
from dalle_pytorch_tpu.serving.router import (
    FleetRouter,
    QuarantineTracker,
    request_fingerprint,
)
from dalle_pytorch_tpu.serving.supervisor import ReplicaSupervisor
from dalle_pytorch_tpu.training.metrics import MetricsRegistry
from dalle_pytorch_tpu.utils import compile_guard
from dalle_pytorch_tpu.utils.compile_cache import (
    CompileCache,
    boot_fingerprint,
)

from test_continuous import FakeContinuousEngine

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


# ------------------------------------------------------- boot fingerprint


class TestBootFingerprint:
    def test_stable_for_identical_inputs(self):
        kw = dict(
            backend="cpu", mesh_shape="tp=2",
            model_config={"dim": 64, "depth": 2},
            programs=["prefill", "chunk"], jax_version="0.4.37",
        )
        assert boot_fingerprint(**kw) == boot_fingerprint(**kw)

    @pytest.mark.parametrize(
        "drift",
        [
            {"backend": "tpu"},
            {"mesh_shape": "tp=4"},
            {"model_config": {"dim": 65, "depth": 2}},
            {"programs": ["prefill", "chunk", "admit_hit"]},
            {"jax_version": "0.5.0"},
        ],
    )
    def test_any_input_drift_changes_it(self, drift):
        base = dict(
            backend="cpu", mesh_shape="tp=2",
            model_config={"dim": 64, "depth": 2},
            programs=["prefill", "chunk"], jax_version="0.4.37",
        )
        assert boot_fingerprint(**base) != boot_fingerprint(**{**base, **drift})

    def test_program_order_is_canonical(self):
        a = boot_fingerprint(programs=["a", "b"], jax_version="x")
        b = boot_fingerprint(programs=["b", "a"], jax_version="x")
        assert a == b


# ------------------------------------------------------ artifact lifecycle


def _counter(reg, name):
    m = reg.get(name)
    return 0 if m is None else int(m.value)


def _counts(reg):
    return {
        k: _counter(reg, f"dalle_boot_cache_{k}_total")
        for k in ("hits", "misses", "rejects")
    }


@pytest.fixture
def compiled_tiny():
    """One real compiled executable to export (module-tiny: adds ~no
    compile time, and repeat calls hit jax's in-process jit cache)."""
    return jax.jit(lambda x: x * 2 + 1).lower(jnp.ones((4,))).compile()


class TestCompileCacheArtifacts:
    FP_KW = dict(
        backend="cpu", model_config={"toy": 1}, jax_version="pinned",
    )

    def _cache(self, tmp_path, reg=None, programs=("p1",), fp=None):
        cache = CompileCache(tmp_path, registry=reg)
        cache.bind(
            fp or boot_fingerprint(programs=list(programs), **self.FP_KW),
            programs,
        )
        return cache

    def test_first_boot_is_cold_then_warm(self, tmp_path, compiled_tiny):
        reg = MetricsRegistry()
        c1 = self._cache(tmp_path, reg)
        plan = c1.plan_boot()
        assert plan["mode"] == "cold"
        assert plan["programs"]["p1"]["status"] == "miss"
        assert c1.wants("p1")
        assert c1.export("p1", compiled_tiny)
        assert not c1.wants("p1")  # exported this boot

        c2 = self._cache(tmp_path, reg)
        plan2 = c2.plan_boot()
        assert plan2["mode"] == "warm", plan2
        assert not c2.wants("p1")  # valid on disk: nothing to re-export
        assert _counts(reg) == {"hits": 1, "misses": 1, "rejects": 0}

    def test_fingerprint_mismatch_degrades_to_cold_miss(
        self, tmp_path, compiled_tiny
    ):
        """The acceptance pin: a config/jax/mesh drift makes the old
        artifacts counted misses and the boot recompiles — it never
        loads a wrong executable and never fails."""
        reg = MetricsRegistry()
        c1 = self._cache(tmp_path, reg)
        c1.plan_boot()
        c1.export("p1", compiled_tiny)
        stale = self._cache(
            tmp_path, reg,
            fp=boot_fingerprint(
                programs=["p1"], **{**self.FP_KW, "model_config": {"toy": 2}}
            ),
        )
        plan = stale.plan_boot()
        assert plan["mode"] == "cold"
        assert plan["programs"]["p1"]["status"] == "miss"
        assert "fingerprint mismatch" in plan["reason"]
        assert _counts(reg)["misses"] == 2  # first boot + the stale one
        # the mismatched boot re-exports under ITS fingerprint...
        assert stale.wants("p1")
        assert stale.export("p1", compiled_tiny)
        # ...and its successor boot is warm
        again = self._cache(tmp_path, reg, fp=stale.fingerprint)
        assert again.plan_boot()["mode"] == "warm"

    @pytest.mark.parametrize("mode", ["truncate", "garble"])
    def test_corrupt_artifact_rejected_not_fatal(
        self, tmp_path, compiled_tiny, mode
    ):
        """The acceptance pin: a torn write / bit rot lands in the
        REJECT branch (counted) and the boot is cold — plan_boot never
        raises on a bad cache."""
        reg = MetricsRegistry()
        c1 = self._cache(tmp_path, reg)
        c1.plan_boot()
        c1.export("p1", compiled_tiny)
        c2 = self._cache(tmp_path, reg)
        c2.faults = FaultInjector().corrupt_cache("p1", mode=mode)
        plan = c2.plan_boot()
        assert plan["mode"] == "cold"
        assert plan["programs"]["p1"]["status"] == "reject"
        assert _counts(reg)["rejects"] == 1
        assert c2.faults.fired and c2.faults.fired[0]["mode"] == mode
        # the reject re-arms the export path: recompile-and-export heals
        assert c2.wants("p1")
        assert c2.export("p1", compiled_tiny)
        c3 = self._cache(tmp_path, reg)
        assert c3.plan_boot()["mode"] == "warm"

    def test_bad_magic_and_stray_file_reject(self, tmp_path):
        reg = MetricsRegistry()
        c = self._cache(tmp_path, reg)
        c.artifact_path("p1").write_bytes(b"not an artifact at all")
        plan = c.plan_boot()
        assert plan["programs"]["p1"]["status"] == "reject"
        assert "magic" in plan["programs"]["p1"]["reason"]

    def test_partial_ladder_is_cold_and_export_carries_forward(
        self, tmp_path, compiled_tiny
    ):
        reg = MetricsRegistry()
        programs = ("p1", "p2")
        c1 = self._cache(tmp_path, reg, programs=programs)
        c1.plan_boot()
        c1.export("p1", compiled_tiny)
        # p2 missing -> cold; p1 stays a hit
        c2 = self._cache(tmp_path, reg, programs=programs)
        plan = c2.plan_boot()
        assert plan["mode"] == "cold"
        assert plan["programs"]["p1"]["status"] == "hit"
        assert plan["programs"]["p2"]["status"] == "miss"
        assert not c2.wants("p1") and c2.wants("p2")
        c2.export("p2", compiled_tiny)
        # manifest carried p1 forward: the full ladder is now warm
        c3 = self._cache(tmp_path, reg, programs=programs)
        assert c3.plan_boot()["mode"] == "warm"

    def test_serialize_failure_is_recorded_not_raised(
        self, tmp_path, compiled_tiny
    ):
        c = self._cache(tmp_path)
        c.plan_boot()
        c._serialize = lambda compiled: (_ for _ in ()).throw(
            RuntimeError("backend cannot serialize")
        )
        assert c.export("p1", compiled_tiny) is False
        assert "cannot serialize" in c.detail()["errors"]["p1"]

    def test_deserialize_seam_and_invalid_artifact(
        self, tmp_path, compiled_tiny
    ):
        c = self._cache(tmp_path)
        c.plan_boot()
        c.export("p1", compiled_tiny)
        # a backend that CAN deserialize gets the payload back through
        # the seam; the default CPU backend degrades to None, never raises
        c._deserialize = lambda blob: ("loaded", len(blob))
        loaded = c.deserialize("p1")
        assert loaded is not None and loaded[0] == "loaded"
        assert c.deserialize("never-exported") is None

    def test_boot_phase_gauge(self, tmp_path):
        reg = MetricsRegistry()
        c = CompileCache(tmp_path, registry=reg)
        with c.boot_phase("warmup"):
            pass
        assert "warmup" in c.boot_seconds
        fam = reg.get("dalle_boot_seconds")
        assert dict(fam.items())["warmup"].value >= 0.0


# ---------------------------------------- persistent-cache hit accounting


class TestCompileGuardCacheHits:
    def test_same_hlo_second_compile_is_a_cache_hit(self, tmp_path):
        """The warm-boot mechanism at its smallest: with the persistent
        cache configured, compiling a FRESH jit object with identical
        HLO is served from disk — counted as a cache hit, so
        `tally.uncached` is zero. (Fresh lambdas defeat jax's in-process
        caches; the persistent store is the only thing that can hit.)
        Routed through CompileCache.install() — which must also RESET
        jax's latched cache state, since this test process has compiled
        plenty before the dir existed."""
        try:
            CompileCache(tmp_path).install()
            # a factory so both wrappers share ONE source location (HLO
            # op metadata carries file:line; a different line would key
            # a different cache entry) while staying distinct function
            # objects (defeating the in-process jaxpr/jit caches)
            def make():
                return jax.jit(lambda v: v * 3.25 + 0.125)

            x = jnp.arange(7.0) * 1.5  # shape unique to this test
            with compile_guard.track_compiles() as cold:
                make()(x).block_until_ready()
            assert cold.count >= 1 and cold.cache_hits == 0
            with compile_guard.track_compiles() as warm:
                make()(x).block_until_ready()
            assert warm.count >= 1
            assert warm.cache_hits == warm.count
            assert warm.uncached == 0
        finally:
            CompileCache.uninstall()

    def test_uninstall_restores_no_cache(self, tmp_path):
        CompileCache(tmp_path).install()
        assert jax.config.jax_compilation_cache_dir == str(
            Path(tmp_path) / "xla"
        )
        CompileCache.uninstall()
        assert jax.config.jax_compilation_cache_dir is None


# ------------------------------------------------ engine AOT-export ladder


class _LadderHost:
    """Minimal host for `GenerationEngine._capture_cost`: just the three
    attributes the ladder reads."""

    def __init__(self, cost_table=None, compile_cache=None):
        self.cost_table = cost_table
        self.compile_cache = compile_cache
        self.mesh = None


class TestWarmupLadderExport:
    def test_one_compile_feeds_cost_table_and_cache(self, tmp_path):
        from dalle_pytorch_tpu.obs.vitals import ProgramCostTable

        reg = MetricsRegistry()
        cache = CompileCache(tmp_path, registry=reg)
        cache.bind(
            boot_fingerprint(programs=["prog"], jax_version="pin"), ["prog"]
        )
        cache.plan_boot()
        host = _LadderHost(
            cost_table=ProgramCostTable(registry=reg), compile_cache=cache
        )
        x = jnp.arange(11.0)  # unique shape: forces one real compile
        with compile_guard.track_compiles() as tally:
            GenerationEngine._capture_cost(host, "prog", lambda v: v + 2, x)
        assert tally.count == 1, "ladder must lower+compile exactly once"
        assert host.cost_table.has("prog")
        assert "prog" in cache.detail()["exported"]
        assert CompileCache(tmp_path).bind(
            cache.fingerprint, ["prog"]
        ).plan_boot()["mode"] == "warm"
        # idempotent: both consumers satisfied -> no further compiles
        with compile_guard.track_compiles() as again:
            GenerationEngine._capture_cost(host, "prog", lambda v: v + 2, x)
        assert again.count == 0

    def test_cache_only_no_cost_table(self, tmp_path):
        cache = CompileCache(tmp_path)
        cache.bind(boot_fingerprint(programs=["q"], jax_version="pin"), ["q"])
        cache.plan_boot()
        host = _LadderHost(compile_cache=cache)
        GenerationEngine._capture_cost(
            host, "q", lambda v: v - 1, jnp.arange(13.0)
        )
        assert "q" in cache.detail()["exported"]

    def test_program_ladders(self):
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        eng = FakeContinuousEngine()  # no ladder: engines only
        assert not hasattr(eng, "program_ladder")
        stub = object.__new__(ContinuousEngine)
        stub.vae = None  # tokens-only engine never compiles decode_pixels
        stub.resume_enabled = False
        stub.preview_enabled = False
        assert ContinuousEngine.program_ladder(stub) == (
            "prefill", "chunk", "release",
        )
        stub.vae = DiscreteVAE(
            image_size=16, num_layers=2, num_tokens=8,
            codebook_dim=4, hidden_dim=4,
        )
        assert ContinuousEngine.program_ladder(stub) == (
            "prefill", "chunk", "release", "decode_pixels",
        )
        # decode-state resume grows the ladder (and with it the boot
        # fingerprint): a resume-enabled build must never claim another
        # build's warm cache
        stub.resume_enabled = True
        assert ContinuousEngine.program_ladder(stub) == (
            "prefill", "resume", "chunk", "release", "decode_pixels",
        )
        # so does the streaming preview fill+decode program
        stub.preview_enabled = True
        assert ContinuousEngine.program_ladder(stub) == (
            "prefill", "resume", "chunk", "release", "decode_pixels",
            "preview",
        )


# ----------------------------------------------------- crash fault kinds


class TestCrashFault:
    def test_crash_rule_aborts_at_exactly_nth(self):
        calls = []
        inj = FaultInjector().crash_nth("chunk", 3, exit_code=71)
        inj._abort = lambda program, nth, code: calls.append(
            (program, nth, code)
        )
        for _ in range(2):
            inj.on_dispatch("chunk")
        assert calls == []
        inj.on_dispatch("chunk")
        assert calls == [("chunk", 3, 71)]
        inj.on_dispatch("chunk")  # one-shot
        assert len(calls) == 1
        assert inj.fired[0]["kind"] == "crash"

    def test_corrupt_rule_is_one_shot_and_counts(self, tmp_path):
        p = tmp_path / "a.aotx"
        p.write_bytes(b"x" * 100)
        inj = FaultInjector().corrupt_cache("a", nth=2, mode="truncate")
        inj.on_artifact_load("a", p)
        assert p.read_bytes() == b"x" * 100  # nth=2: first load untouched
        inj.on_artifact_load("a", p)
        assert len(p.read_bytes()) == 50
        inj.on_artifact_load("a", p)
        assert len(p.read_bytes()) == 50  # fired once
        assert [f["nth"] for f in inj.fired] == [2]

    def test_corrupt_missing_file_stays_missing(self, tmp_path):
        inj = FaultInjector().corrupt_cache("ghost")
        inj.on_artifact_load("ghost", tmp_path / "ghost.aotx")
        assert not (tmp_path / "ghost.aotx").exists()


# ----------------------------------------------------- supervisor policy


class _Log:
    def __init__(self):
        self.events = []

    def event(self, name, **fields):
        self.events.append({"event": name, **fields})

    def of(self, name):
        return [e for e in self.events if e["event"] == name]


def _sup(**kw):
    kw.setdefault("argv", ["true"])
    kw.setdefault("backoff_base_s", 0.5)
    kw.setdefault("backoff_max_s", 8.0)
    kw.setdefault("crash_loop_exits", 3)
    kw.setdefault("crash_loop_window_s", 60.0)
    kw.setdefault("hold_down_s", 300.0)
    return ReplicaSupervisor(**kw)


class TestSupervisorPolicy:
    def test_backoff_schedule_is_capped_exponential(self):
        sup = _sup()
        assert [sup.backoff_schedule(n) for n in range(1, 7)] == [
            0.5, 1.0, 2.0, 4.0, 8.0, 8.0,
        ]

    def test_consecutive_failures_double_the_delay(self):
        """The acceptance pin: the restart schedule, driven purely
        through the injectable clock."""
        log = _Log()
        sup = _sup(log=log)
        delays = []
        now = 1000.0
        for i in range(4):
            # fast exits, far apart enough not to trip the 3-in-60s
            # window (spacing 100s > window)
            now += 100.0
            delays.append(sup._on_exit(70, now, uptime_s=1.0, was_ready=True))
        assert delays == [0.5, 1.0, 2.0, 4.0]
        assert sup.crash_loops == 0
        assert sup.last_exit_reason == "exit 70"

    def test_stable_run_resets_the_streak(self):
        sup = _sup()
        assert sup._on_exit(70, 100.0, uptime_s=1.0, was_ready=True) == 0.5
        assert sup._on_exit(70, 200.0, uptime_s=1.0, was_ready=True) == 1.0
        # a long-healthy child failing is a fresh incident
        assert sup._on_exit(
            70, 400.0, uptime_s=sup.stable_reset_s + 1, was_ready=True
        ) == 0.5

    def test_crash_loop_hold_down_inside_window(self):
        """The acceptance pin: the third abnormal exit inside the 60s
        window holds the replica down and emits the structured
        crash_loop event + metric."""
        reg = MetricsRegistry()
        log = _Log()
        sup = _sup(log=log, registry=reg)
        assert sup._on_exit(70, 10.0, 1.0, True) == 0.5
        assert sup._on_exit(70, 20.0, 1.0, True) == 1.0
        assert sup._on_exit(70, 30.0, 1.0, True) == 300.0  # hold-down
        assert sup.state == "held_down"
        assert sup.crash_loops == 1
        assert reg.get("dalle_supervisor_crash_loops_total").value == 1
        (ev,) = log.of("crash_loop")
        assert ev["exits"] == 3 and ev["hold_down_s"] == 300.0
        # the window cleared: the next exit backs off normally
        assert sup._on_exit(70, 31.0, 1.0, True) in (0.5, 1.0, 2.0, 4.0, 8.0)

    def test_exits_outside_window_never_hold_down(self):
        sup = _sup()
        for i in range(6):
            delay = sup._on_exit(
                70, 1000.0 * (i + 1), uptime_s=1.0, was_ready=True
            )
            assert delay < sup.hold_down_s
        assert sup.crash_loops == 0

    def test_clean_exit_ends_supervision(self):
        sup = _sup()
        assert sup._on_exit(0, 10.0, 5.0, True) is None
        assert sup.last_exit_reason == "clean"

    def test_signal_exit_reason(self):
        sup = _sup()
        sup._on_exit(-9, 10.0, 1.0, True)
        assert sup.last_exit_reason == "signal 9"


class _FakeProc:
    """Scripted child: alive until `die(code)` is called."""

    def __init__(self, pid):
        self.pid = pid
        self._code = None
        self._died = threading.Event()
        self.terminated = False

    def die(self, code):
        self._code = code
        self._died.set()

    def poll(self):
        return self._code

    def wait(self, timeout=None):
        if not self._died.wait(timeout):
            import subprocess

            raise subprocess.TimeoutExpired("fake", timeout)
        return self._code

    def terminate(self):
        self.terminated = True
        self.die(0)

    def kill(self):
        self.die(-9)


class TestSupervisorRun:
    def test_restart_after_abnormal_exit_then_clean_stop(self):
        """Scripted end-to-end: child 1 becomes ready then dies
        abnormally; the supervisor restarts it (counted, logged); child
        2 serves until stop() terminates it."""
        log = _Log()
        procs = []

        def spawn():
            p = _FakeProc(pid=100 + len(procs))
            procs.append(p)
            return p

        ready = threading.Event()
        sup = _sup(
            log=log, registry=MetricsRegistry(),
            spawn_fn=spawn, probe_fn=lambda: True,
            backoff_base_s=0.01, probe_interval_s=0.01,
        )
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while not procs and time.monotonic() < deadline:
            time.sleep(0.01)
        procs[0].die(70)  # abnormal: supervisor must respawn
        while len(procs) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(procs) == 2, "no restart after abnormal exit"
        while sup.state != "serving" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sup.restarts == 1
        sup.stop()
        t.join(timeout=10)
        assert not t.is_alive()
        assert procs[1].terminated
        assert [e["event"] for e in log.events].count("replica_ready") >= 2
        assert log.of("replica_exit")[0]["code"] == 70

    def test_hung_boot_is_recycled_at_ready_timeout(self):
        """A child that is alive but never answers /healthz inside
        ready_timeout_s is killed and restarted through the normal
        abnormal-exit path — even when it honors SIGTERM with a clean
        exit 0, supervision must continue (the replica never served)."""
        procs = []

        def spawn():
            p = _FakeProc(pid=300 + len(procs))
            procs.append(p)
            return p

        sup = _sup(
            spawn_fn=spawn, probe_fn=lambda: False,  # never ready
            ready_timeout_s=0.2, probe_interval_s=0.02,
            backoff_base_s=0.01,
        )
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while len(procs) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(procs) >= 2, "hung boot was never recycled"
        assert procs[0].terminated  # killed, not abandoned
        assert sup.restarts >= 1
        sup.stop()
        t.join(timeout=10)

    def test_readiness_gates_on_probe(self):
        """`serving` (and time-to-ready) requires the probe to answer —
        a half-booted child never reads as ready."""
        probe_ok = threading.Event()
        procs = []

        def spawn():
            p = _FakeProc(pid=1)
            procs.append(p)
            return p

        sup = _sup(
            spawn_fn=spawn, probe_fn=probe_ok.is_set,
            probe_interval_s=0.01,
        )
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        time.sleep(0.1)
        assert sup.state == "starting" and sup.last_ready_s is None
        probe_ok.set()
        deadline = time.monotonic() + 5
        while sup.state != "serving" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sup.state == "serving"
        assert sup.last_ready_s is not None and sup.last_ready_s >= 0.0
        sup.stop()
        t.join(timeout=5)


# ------------------------------------------------------------- quarantine


class TestQuarantineTracker:
    def test_threshold_and_absolve(self):
        q = QuarantineTracker(after=2)
        i1 = q.mint_incident("r0", "boom", ["k"])
        assert q.implicate("k", i1) == 1
        assert not q.is_quarantined("k")
        q.absolve("k")
        i2 = q.mint_incident("r1", "boom", ["k"])
        assert q.implicate("k", i2) == 1  # streak reset by the absolve
        i3 = q.mint_incident("r2", "boom", ["k"])
        assert q.implicate("k", i3) == 2
        assert q.is_quarantined("k")
        assert q.incidents_for("k") == [i2, i3]

    def test_one_replica_death_is_one_incident(self):
        """Coalescing: N dispatch threads reporting the same severed
        replica within the window share an incident id, and charging a
        key twice with it is idempotent."""
        clock = [100.0]
        q = QuarantineTracker(
            after=2, coalesce_window_s=5.0, time_fn=lambda: clock[0]
        )
        a = q.mint_incident("r0", "reset", ["x"])
        clock[0] += 1.0
        b = q.mint_incident("r0", "reset again", ["x", "y"])
        assert a == b
        assert q.implicate("x", a) == 1
        assert q.implicate("x", b) == 1  # same incident: no double charge
        clock[0] += 10.0  # window expired: a NEW death is a new incident
        c = q.mint_incident("r0", "reset", ["x"])
        assert c != a
        assert q.implicate("x", c) == 2
        assert q.is_quarantined("x")

    def test_capacity_bound(self):
        q = QuarantineTracker(after=3, capacity=4)
        inc = q.mint_incident("r0", "e", [])
        for i in range(10):
            q.implicate(f"k{i}", inc)
        assert q.detail()["tracked_keys"] <= 4

    def test_quarantine_expires_after_ttl(self):
        """A quarantined key is refused at ingress, so success can never
        absolve it — the TTL is the only way back. Without it, a
        fleet-wide transport blip that walked one request across K dead
        replicas would brick its fingerprint until a router restart."""
        clock = [0.0]
        q = QuarantineTracker(
            after=2, coalesce_window_s=0.0, ttl_s=60.0,
            time_fn=lambda: clock[0],
        )
        for replica in ("r0", "r1"):
            clock[0] += 1.0
            q.implicate("k", q.mint_incident(replica, "blip", ["k"]))
        assert q.is_quarantined("k")
        clock[0] += 59.0
        assert q.is_quarantined("k")  # still inside the TTL
        clock[0] += 2.0
        assert not q.is_quarantined("k")  # lifted
        # and a fresh implication starts a NEW streak, not count 3
        clock[0] += 1.0
        assert q.implicate(
            "k", q.mint_incident("r2", "again", ["k"])
        ) == 1

    def test_eviction_never_evicts_the_key_being_charged(self):
        """At capacity with every OTHER key quarantined, the eviction
        fallback must pop an old quarantined mark — never the key being
        inserted right now (that would make new poison untrackable)."""
        clock = [0.0]
        q = QuarantineTracker(
            after=1, capacity=2, coalesce_window_s=0.0,
            time_fn=lambda: clock[0],
        )

        def inc(r):
            clock[0] += 1.0
            return q.mint_incident(r, "e", [])

        q.implicate("old1", inc("a"))  # quarantined (after=1)
        q.implicate("old2", inc("b"))  # quarantined
        assert q.implicate("fresh", inc("c")) == 1  # charge must stick
        assert q.is_quarantined("fresh")
        assert q.detail()["tracked_keys"] <= 2

    def test_eviction_churn_cannot_erase_a_live_quarantine(self):
        """absolve + re-implicate + capacity churn: the freshly
        quarantined key must survive eviction (a stale side-ordering
        would evict the live mark and let a replica-killer back in)."""
        clock = [0.0]
        q = QuarantineTracker(
            after=2, capacity=4, coalesce_window_s=0.0,
            time_fn=lambda: clock[0],
        )

        def inc(replica):
            clock[0] += 1.0
            return q.mint_incident(replica, "e", [])

        q.implicate("poison", inc("a"))
        q.absolve("poison")  # stale entry in any side ordering
        q.implicate("poison", inc("b"))
        q.implicate("poison", inc("c"))
        assert q.is_quarantined("poison")
        for i in range(10):  # churn well past capacity
            q.implicate(f"bystander{i}", inc(f"r{i}"))
        assert q.is_quarantined("poison"), (
            "capacity churn evicted a freshly-quarantined key"
        )


class TestRequestFingerprint:
    def test_excludes_timeout_includes_content(self):
        a = request_fingerprint({"prompt": "x", "timeout_s": 5})
        b = request_fingerprint({"prompt": "x", "timeout_s": 99})
        c = request_fingerprint({"prompt": "y", "timeout_s": 5})
        assert a == b and a != c

    def test_key_order_insensitive_and_seed_sensitive(self):
        a = request_fingerprint({"prompt": "x", "num_images": 2})
        b = request_fingerprint({"num_images": 2, "prompt": "x"})
        assert a == b
        assert request_fingerprint({"prompt": "x", "seed": 1}) != (
            request_fingerprint({"prompt": "x", "seed": 2})
        )


def _mk_router(post_fn, replicas=2, **kw):
    kw.setdefault("quarantine_after", 2)
    kw.setdefault("retry_budget_initial", 10.0)
    # breaker kept out of the way: these tests pin quarantine behavior
    kw.setdefault("error_min_samples", 10_000)
    router = FleetRouter(
        [f"r{i}=http://127.0.0.1:{59000 + i}" for i in range(replicas)],
        registry=MetricsRegistry(),
        **kw,
    )
    router._post = post_fn
    return router


def _route(router, body, headers=None):
    return router.handle_generate(json.dumps(body).encode(), headers or {})


_OK_BODY = json.dumps({"tokens": [[1, 2]]}).encode()


class TestRouterQuarantine:
    def test_poison_quarantined_at_exactly_k_innocent_survives(self):
        """The acceptance satellite, end to end through the real router
        policy loop: a poison request crashes two replicas in a row and
        is quarantined at EXACTLY K=2 incidents (terminal 422 carrying
        both ids); the innocent request that was in flight on the second
        crashed replica fails over and completes — its single bystander
        implication is coalesced with its own failed dispatch (one
        replica death = one incident) and its success absolves it."""
        innocent_on_r0 = threading.Event()
        poison_done = threading.Event()
        calls = {"poison": 0, "innocent": 0}

        def post(rep, payload, headers, timeout_s, conns):
            body = json.loads(payload)
            if body["prompt"] == "innocent":
                calls["innocent"] += 1
                if calls["innocent"] == 1:
                    innocent_on_r0.set()
                    assert poison_done.wait(20)
                    raise ConnectionResetError("r0 died under poison")
                return 200, _OK_BODY, {}
            calls["poison"] += 1
            assert innocent_on_r0.wait(20)
            raise ConnectionResetError(f"{rep.name} killed by poison")

        router = _mk_router(post)
        results = {}

        def run_innocent():
            results["innocent"] = _route(
                router, {"prompt": "innocent", "seed": 1}
            )

        t = threading.Thread(target=run_innocent, daemon=True)
        t.start()
        assert innocent_on_r0.wait(20)  # innocent inflight on r0
        status, body, _ = _route(router, {"prompt": "poison", "seed": 2})
        poison_done.set()
        t.join(timeout=30)
        assert not t.is_alive()

        assert status == 422
        payload = json.loads(body)
        assert len(payload["incidents"]) == 2, payload  # exactly K
        assert calls["poison"] == 2  # one crash per incident, then stopped
        # the innocent survived failover and is absolved
        inn_status, inn_body, _ = results["innocent"]
        assert inn_status == 200
        assert not router.quarantine.is_quarantined(
            request_fingerprint({"prompt": "innocent", "seed": 1})
        )
        # resubmitting the identical poison body is refused AT INGRESS:
        # zero further dispatches
        status2, body2, _ = _route(router, {"prompt": "poison", "seed": 2})
        assert status2 == 422
        assert json.loads(body2)["incidents"] == payload["incidents"]
        assert calls["poison"] == 2
        assert router.registry.get(
            "dalle_router_quarantined_total"
        ).value == 2

    def test_http_5xx_does_not_implicate(self):
        """A replica that ANSWERS 5xx survived — request-scoped engine
        poison is the replica's own (batcher-side) quarantine; the
        router must not crash-implicate it."""

        def post(rep, payload, headers, timeout_s, conns):
            return 500, json.dumps({"error": "engine fell over"}).encode(), {}

        router = _mk_router(post, replicas=1, retry_budget_initial=2.0)
        status, _, _ = _route(router, {"prompt": "x", "seed": 3})
        # retried until the budget drained (failover semantics for 5xx
        # are unchanged), but the quarantine ledger never moved
        assert status in (500, 503)
        assert router.quarantine.detail()["tracked_keys"] == 0

    def test_socket_timeout_does_not_implicate(self):
        """A client-side timeout means the replica was SLOW, not dead —
        a fleet-wide slow spell must not quarantine a popular prompt
        that keeps timing out without ever succeeding."""
        import socket

        def post(rep, payload, headers, timeout_s, conns):
            raise socket.timeout("read timed out")

        router = _mk_router(post, replicas=1, retry_budget_initial=2.0)
        status, _, _ = _route(router, {"prompt": "slow", "seed": 9})
        assert status == 503  # budget-bounded failover, never a 422
        assert router.quarantine.detail()["tracked_keys"] == 0

    def test_hedge_cancellation_does_not_implicate(self):
        """A hedge win closes the loser's connection; the loser's
        resulting transport error is OUR cancellation, not crash
        evidence against a healthy replica."""

        def post(rep, payload, headers, timeout_s, conns):
            return 200, _OK_BODY, {}

        router = _mk_router(post, replicas=1)
        rep = router.replicas[0]
        res = {
            "kind": "error", "replica": rep,
            "error": ConnectionResetError("we closed it"),
            "hedged": True, "cancelled": True,
        }
        assert router._settle(res, rep, klass=1, key="k") == "failover"
        assert router.quarantine.detail()["tracked_keys"] == 0
        # the same error WITHOUT the cancellation flag does implicate
        res2 = dict(res, cancelled=False)
        router._settle(res2, rep, klass=1, key="k")
        assert router.quarantine.detail()["tracked_keys"] == 1

    def test_success_clears_prior_implication(self):
        flaky = {"left": 1}

        def post(rep, payload, headers, timeout_s, conns):
            if flaky["left"] > 0:
                flaky["left"] -= 1
                raise ConnectionResetError("one-off crash")
            return 200, _OK_BODY, {}

        router = _mk_router(post)
        status, _, _ = _route(router, {"prompt": "x", "seed": 4})
        assert status == 200
        assert router.quarantine.detail()["tracked_keys"] == 0

    def test_quarantine_disabled_with_zero(self):
        def post(rep, payload, headers, timeout_s, conns):
            raise ConnectionResetError("crash")

        router = _mk_router(
            post, replicas=1, quarantine_after=0,
            retry_budget_initial=2.0,
        )
        status, _, _ = _route(router, {"prompt": "x", "seed": 5})
        assert status == 503  # budget exhaustion, never a 422
        assert router.quarantine is None

    def test_debug_detail_carries_quarantine_block(self):
        def post(rep, payload, headers, timeout_s, conns):
            return 200, _OK_BODY, {}

        router = _mk_router(post)
        d = router.detail()
        assert d["quarantine"]["after"] == 2
        assert "tracked_keys" in d["quarantine"]


class TestRestartAttribution:
    def test_eject_recover_records_restart_and_rejoin(self):
        """Router-side restart accounting: ejection stamps the outage
        start + reason; the half-open trial that closes the circuit
        counts one restart and measures time-to-rejoin."""
        clock = [1000.0]

        def post(rep, payload, headers, timeout_s, conns):
            return 200, _OK_BODY, {}

        router = _mk_router(post, replicas=1, time_fn=lambda: clock[0])
        rep = router.replicas[0]
        rep.last_error = "connection refused"
        with router._lock:
            router._eject(rep, "probe", clock[0])
        assert rep.down_at == 1000.0
        assert rep.last_down_reason == "probe: connection refused"
        clock[0] += 12.5
        # probe succeeds -> half_open; the trial dispatch closes it
        router._on_probe(rep, 200, {"status": "ok"}, clock[0])
        assert rep.health == "half_open"
        rep.trial_inflight = True
        router._record_dispatch(rep, ok=True)
        assert rep.health == "healthy"
        assert rep.restarts == 1
        assert rep.last_rejoin_s == pytest.approx(12.5)
        assert rep.down_at is None
        d = rep.detail(clock[0])
        assert d["restarts"] == 1
        assert d["last_rejoin_s"] == pytest.approx(12.5)
        assert d["last_down_reason"] == "probe: connection refused"

    def test_flapping_keeps_original_down_timestamp(self):
        clock = [100.0]

        def post(rep, payload, headers, timeout_s, conns):
            return 200, _OK_BODY, {}

        router = _mk_router(post, replicas=1, time_fn=lambda: clock[0])
        rep = router.replicas[0]
        with router._lock:
            router._eject(rep, "probe", 100.0)
        clock[0] = 150.0
        router._on_probe(rep, 200, {}, 150.0)  # half_open
        rep.trial_inflight = True
        router._record_dispatch(rep, ok=False)  # trial fails: re-eject
        assert rep.health == "ejected"
        assert rep.down_at == 100.0  # the ORIGINAL outage start
        clock[0] = 180.0
        router._on_probe(rep, 200, {}, 180.0)
        rep.trial_inflight = True
        router._record_dispatch(rep, ok=True)
        assert rep.restarts == 1
        assert rep.last_rejoin_s == pytest.approx(80.0)


# ------------------------------------------------- batcher-side incidents


class TestBatcherIncidents:
    def test_continuous_dispatch_failures_attribute_incidents(self):
        """A request in flight for two consecutive failed dispatches
        carries two distinct incident ids when it finally fails — the
        ledger the HTTP layer's 422 mapping reads."""
        eng = FakeContinuousEngine()
        eng.fail_chunks = True
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            req = b.submit([SampleSpec(np.zeros(8, np.int32), seed=1)])
            with pytest.raises(RuntimeError, match="XLA fell over"):
                req.future.result(timeout=30)
            assert len(req.incidents) == 2
            assert len(set(req.incidents)) == 2
            assert req.dispatch_retries == 1
        finally:
            eng.fail_chunks = False
            b.shutdown(drain=False)

    def test_successful_dispatch_clears_the_streak(self):
        """Incidents are CONSECUTIVE (mirroring the router's
        absolve-on-success): a one-off failure's implication is erased
        by the next successful chunk, so a long-running bystander that
        later dies in an unrelated incident is a 500, never a 422."""
        eng = FakeContinuousEngine()
        flips = {"left": 1}
        orig = eng.step_chunk

        def flaky():
            if flips["left"] > 0:
                flips["left"] -= 1
                raise RuntimeError("one-off")
            return orig()

        eng.step_chunk = flaky
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            req = b.submit([SampleSpec(np.zeros(8, np.int32), seed=2)])
            req.future.result(timeout=30)
            assert req.incidents == []  # cleared by the successful chunks
            assert req.dispatch_retries == 1  # it WAS implicated once
        finally:
            b.shutdown(drain=False)

    def test_micro_flush_failure_attributes_one_incident(self):
        class FailingEngine:
            max_batch = 2

            def generate(self, specs):
                raise RuntimeError("boom")

        b = MicroBatcher(
            FailingEngine(), max_delay_ms=1, registry=MetricsRegistry()
        )
        try:
            req = b.submit([SampleSpec(np.zeros(8, np.int32), seed=3)])
            with pytest.raises(RuntimeError, match="boom"):
                req.future.result(timeout=30)
            assert len(req.incidents) == 1
        finally:
            b.shutdown(drain=False)


# --------------------------------------- real engine: HTTP 422 quarantine


@pytest.fixture(scope="module")
def toy():
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


def _post_generate(port, body, timeout=60.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


_WARMBOOT_SCRIPT = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
cache_dir = sys.argv[1]
import jax, jax.numpy as jnp, numpy as np
from dalle_pytorch_tpu.utils.compile_cache import CompileCache, boot_fingerprint
from dalle_pytorch_tpu.utils import compile_guard
from dalle_pytorch_tpu.training.metrics import MetricsRegistry
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.serving.engine import ContinuousEngine, SampleSpec

t0 = time.perf_counter()
reg = MetricsRegistry()
cache = CompileCache(cache_dir, registry=reg).install()
TEXT_SEQ, FMAP = 8, 4
model = DALLE(dim=32, depth=2, heads=2, dim_head=8, num_image_tokens=32,
              image_fmap_size=FMAP, num_text_tokens=64, text_seq_len=TEXT_SEQ,
              shift_tokens=True, rotary_emb=True)
params = jax.jit(model.init)(
    jax.random.PRNGKey(42), jnp.zeros((1, TEXT_SEQ), jnp.int32),
    jnp.zeros((1, FMAP * FMAP), jnp.int32),
)
eng = ContinuousEngine(model=model, variables=params, max_batch=2,
                       chunk_tokens=4, prefill_batch=2, registry=reg)
fp = boot_fingerprint(backend=jax.default_backend(),
                      model_config={"toy": "warmboot"},
                      programs=eng.program_ladder())
cache.bind(fp, eng.program_ladder())
plan = cache.plan_boot()
eng.compile_cache = cache
with compile_guard.track_compiles() as warm_tally:
    eng.warmup()
with compile_guard.track_compiles() as serve_tally:
    eng.prefill_slot(0, SampleSpec(np.zeros(TEXT_SEQ, np.int32), seed=7))
    for _ in range(FMAP * FMAP // 4):
        eng.step_chunk()
    toks = eng.harvest([0])
    eng.release([0])
    eng.decode_pixels(toks)
print("WARMBOOT " + json.dumps({
    "mode": plan["mode"],
    "warmup_compiles": warm_tally.count,
    "warmup_uncached": warm_tally.uncached,
    "serve_compiles": serve_tally.count,
    "serve_uncached": serve_tally.uncached,
    "boot_s": round(time.perf_counter() - t0, 2),
}))
"""


@pytest.mark.slow
class TestWarmSecondBoot:
    def test_second_boot_zero_uncached_compiles_full_serve_cycle(
        self, tmp_path
    ):
        """THE acceptance pin, across two real process boots: boot 1
        compiles the continuous ladder cold and exports it; boot 2 (same
        fingerprint, fresh process) runs warmup AND a full serve cycle
        (admit -> chunks -> harvest -> release -> pixel decode) with
        ZERO uncached backend compiles — every compilation is a
        persistent-cache load, counted by compile_guard."""
        import subprocess
        import sys

        script = tmp_path / "warmboot.py"
        script.write_text(_WARMBOOT_SCRIPT)
        cache_dir = tmp_path / "cache"

        def boot():
            env = dict(__import__("os").environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = "/root/repo"
            out = subprocess.run(
                [sys.executable, str(script), str(cache_dir)],
                capture_output=True, text=True, timeout=600,
                cwd="/root/repo", env=env,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            line = [
                ln for ln in out.stdout.splitlines()
                if ln.startswith("WARMBOOT ")
            ]
            assert line, out.stdout
            return json.loads(line[-1][len("WARMBOOT "):])

        cold = boot()
        assert cold["mode"] == "cold"
        assert cold["warmup_uncached"] > 0
        assert cold["serve_uncached"] == 0  # warmup covers the ladder

        warm = boot()
        assert warm["mode"] == "warm", warm
        assert warm["warmup_uncached"] == 0, warm
        assert warm["serve_compiles"] == 0, warm
        assert warm["serve_uncached"] == 0, warm


class _ServerProc:
    """Process facade over an in-process ServingServer, so the REAL
    supervisor loop can hard-kill and respawn a REAL HTTP replica
    without paying subprocess jax boots."""

    _next_pid = [50000]

    def __init__(self, server):
        self.server = server
        self.pid = self._next_pid[0]
        self._next_pid[0] += 1
        self._code = None
        self._died = threading.Event()

    def die(self, code):
        """Hard kill: intake refused, queue failed, no drain."""
        if self._code is None:
            self.server.shutdown(drain=False)
            self._code = code
            self._died.set()

    def poll(self):
        return self._code

    def wait(self, timeout=None):
        if not self._died.wait(timeout):
            import subprocess

            raise subprocess.TimeoutExpired("in-process replica", timeout)
        return self._code

    def terminate(self):
        self.die(0)

    def kill(self):
        self.die(-9)


@pytest.mark.slow
class TestSupervisedRecovery:
    def test_hard_kill_mid_window_restarts_rejoins_zero_client_errors(
        self, toy
    ):
        """The fleet acceptance pin: requests flow through a real router
        over real sockets; one replica is HARD-KILLED mid-window; its
        supervisor restarts it, the router walks it back in through
        half-open, and 100% of offered requests complete with no
        client-visible errors (failover covers the outage)."""
        import socket

        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
        from dalle_pytorch_tpu.serving.router import RouterServer
        from dalle_pytorch_tpu.serving.server import ServingServer

        model, params = toy

        def make_engine():
            eng = ContinuousEngine(
                model=model, variables=params, max_batch=2,
                chunk_tokens=2, prefill_batch=2,
                registry=MetricsRegistry(),
            )
            eng.tokenizer = ByteTokenizer()
            return eng

        # r0's port must survive restarts (the router's URL is fixed)
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        r0_port = probe.getsockname()[1]
        probe.close()

        engine0 = make_engine()  # host process survives the "crash"
        procs = []

        def spawn():
            try:  # the kill may leave device rows active: reset them
                engine0.release(range(engine0.max_batch))
            except Exception:
                pass
            proc = _ServerProc(
                ServingServer(engine0, port=r0_port).start()
            )
            procs.append(proc)
            return proc

        # backoff long enough that the router's health probes observe
        # the outage (3 consecutive failures at 0.1s -> ejected) before
        # the replica is back — the rejoin must walk the real
        # ejected -> half_open -> trial -> healthy path
        sup = ReplicaSupervisor(
            ["in-process"], spawn_fn=spawn,
            health_url=f"http://127.0.0.1:{r0_port}/healthz",
            registry=MetricsRegistry(), log=_Log(),
            backoff_base_s=1.5, probe_interval_s=0.05,
        )
        sup_thread = threading.Thread(target=sup.run, daemon=True)
        sup_thread.start()

        server1 = ServingServer(make_engine(), port=0).start()
        router = FleetRouter(
            [
                f"r0=http://127.0.0.1:{r0_port}",
                f"r1=http://127.0.0.1:{server1.port}",
            ],
            registry=MetricsRegistry(),
            probe_interval_s=0.1,
            attempt_timeout_s=60.0,
        )
        front = RouterServer(router, port=0).start()
        try:
            # warm both replicas (compile + prove routing works)
            for i in range(4):
                status, payload = _post_generate(
                    front.port, {"prompt": "warm", "seed": 1000 + i},
                    timeout=180,
                )
                assert status == 200, payload

            statuses = {}

            def client(i):
                statuses[i] = _post_generate(
                    front.port, {"prompt": f"win {i}", "seed": i},
                    timeout=180,
                )[0]

            threads = []
            n = 16
            for i in range(n):
                t = threading.Thread(target=client, args=(i,), daemon=True)
                t.start()
                threads.append(t)
                time.sleep(0.15)
                if i == n // 3:
                    procs[-1].die(70)  # HARD KILL r0 mid-window
            for t in threads:
                t.join(timeout=180)
            assert all(not t.is_alive() for t in threads)

            # 100% completion, zero client-visible errors
            assert sorted(statuses) == list(range(n))
            assert all(s == 200 for s in statuses.values()), statuses

            # the replica restarted under supervision...
            deadline = time.monotonic() + 60
            while sup.restarts < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert sup.restarts == 1
            assert len(procs) == 2

            # ...and rejoined the fleet through half-open: drive traffic
            # until the router's attribution shows the restart
            rep0 = router.replicas[0]
            i = 0
            while rep0.restarts < 1 and time.monotonic() < deadline:
                _post_generate(
                    front.port, {"prompt": f"rejoin {i}", "seed": 5000 + i},
                    timeout=180,
                )
                i += 1
                time.sleep(0.1)
            assert rep0.restarts == 1, rep0.detail(time.monotonic())
            detail = rep0.detail(time.monotonic())
            assert detail["last_rejoin_s"] is not None
            assert detail["last_down_reason"] is not None
        finally:
            front.shutdown()
            sup.stop()
            sup_thread.join(timeout=30)
            server1.shutdown(drain=False)


class TestHTTPQuarantine:
    def test_exhausted_poison_request_gets_422_with_incidents(self, toy):
        """Replica-side quarantine over real HTTP: a request whose
        dispatch AND bounded retry both fail (injected) dies with two
        incident ids -> terminal 422 (not a failover-inviting 500),
        counted; the engine then serves the next request normally."""
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
        from dalle_pytorch_tpu.serving.server import ServingServer

        model, params = toy
        eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=2,
            prefill_batch=2, registry=MetricsRegistry(),
        )
        eng.tokenizer = ByteTokenizer()
        server = ServingServer(eng, port=0, request_timeout_s=60).start()
        try:
            eng.faults = (
                FaultInjector()
                .fail_nth("prefill", 1)
                .fail_nth("prefill", 2)
            )
            status, payload = _post_generate(
                server.port, {"prompt": "poison pill", "seed": 7}
            )
            assert status == 422, payload
            assert len(payload["incidents"]) == 2
            assert "quarantined" in payload["error"]
            assert server.registry.get(
                "dalle_serving_quarantined_total"
            ).value == 1
            # rules exhausted: the engine recovered and serves again
            status2, payload2 = _post_generate(
                server.port, {"prompt": "healthy", "seed": 8}
            )
            assert status2 == 200, payload2
        finally:
            server.shutdown(drain=False)
