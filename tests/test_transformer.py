import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.transformer import Transformer

FMAP = 3
SEQ = 4 + FMAP * FMAP - 1  # text_len (incl bos) = 4+1... seq = text+img tokens


def make_transformer(**kw):
    defaults = dict(
        dim=32,
        depth=2,
        seq_len=SEQ,
        heads=2,
        dim_head=8,
        image_fmap_size=FMAP,
        rotary_emb=True,
    )
    defaults.update(kw)
    return Transformer(**defaults)


def init_and_run(tfm, n=SEQ, **call_kw):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, n, 32))
    variables = tfm.init(jax.random.PRNGKey(1), x)
    return variables, tfm.apply(variables, x, **call_kw), x


class TestTransformer:
    @pytest.mark.parametrize(
        "attn_types",
        [("full",), ("axial_row", "axial_col"), ("conv_like",), ("sparse",)],
    )
    def test_forward_shapes(self, attn_types):
        tfm = make_transformer(attn_types=attn_types)
        _, out, x = init_and_run(tfm)
        assert out.shape == x.shape
        assert np.all(np.isfinite(np.asarray(out)))

    @pytest.mark.parametrize(
        "kw",
        [
            {"shift_tokens": True},
            {"sandwich_norm": True},
            {"stable": True},
            {"rotary_emb": False},
            {"reversible": True},
        ],
    )
    def test_feature_flags(self, kw):
        tfm = make_transformer(**kw)
        _, out, x = init_and_run(tfm)
        assert out.shape == x.shape

    @pytest.mark.parametrize(
        "attn_types", [("full",), ("axial_row", "axial_col"), ("conv_like",), ("sparse",)]
    )
    def test_causality(self, attn_types):
        """Perturbing position j must not change outputs at positions < j."""
        tfm = make_transformer(attn_types=attn_types, shift_tokens=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        variables = tfm.init(jax.random.PRNGKey(1), x)
        out1 = tfm.apply(variables, x)
        j = SEQ - 3
        x2 = x.at[:, j].add(10.0)
        out2 = tfm.apply(variables, x2)
        np.testing.assert_allclose(
            np.asarray(out1[:, :j]), np.asarray(out2[:, :j]), atol=1e-5
        )
        assert not np.allclose(np.asarray(out1[:, j:]), np.asarray(out2[:, j:]))

    def test_shared_ids_reduce_params(self):
        full = make_transformer(depth=4)
        shared = make_transformer(depth=4, shared_attn_ids=(0, 1, 0, 1), shared_ff_ids=(0, 0, 0, 0))
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        n_full = sum(g.size for g in jax.tree.leaves(full.init(jax.random.PRNGKey(1), x)))
        n_shared = sum(
            g.size for g in jax.tree.leaves(shared.init(jax.random.PRNGKey(1), x))
        )
        assert n_shared < n_full

    def test_shared_ids_type_mismatch_raises(self):
        tfm = make_transformer(
            depth=2, attn_types=("full", "axial_row"), shared_attn_ids=(0, 0)
        )
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        with pytest.raises(ValueError, match="shared_attn_ids"):
            tfm.init(jax.random.PRNGKey(1), x)

    def test_reverse_model_changes_output(self):
        tfm = make_transformer(depth=3)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        variables = tfm.init(jax.random.PRNGKey(1), x)
        out_fwd = tfm.apply(variables, x)
        out_rev = tfm.apply(variables, x, reverse_model=True)
        assert not np.allclose(np.asarray(out_fwd), np.asarray(out_rev))

    def test_reversible_matches_grads_structure(self):
        """remat-reversible must compute identical outputs to plain mode."""
        tfm_plain = make_transformer(depth=2)
        tfm_rev = make_transformer(depth=2, reversible=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        variables = tfm_plain.init(jax.random.PRNGKey(1), x)

        out_plain = tfm_plain.apply(variables, x)
        out_rev = tfm_rev.apply(variables, x)
        np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_rev), atol=1e-6)

        g1 = jax.grad(lambda p: (tfm_plain.apply({"params": p}, x) ** 2).sum())(
            variables["params"]
        )
        g2 = jax.grad(lambda p: (tfm_rev.apply({"params": p}, x) ** 2).sum())(
            variables["params"]
        )
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_remat_policy_grad_parity(self):
        """A selective checkpoint policy (save matmul outputs, recompute
        elementwise) must not change outputs or grads — only the
        memory/recompute trade."""
        tfm_plain = make_transformer(depth=2)
        tfm_pol = make_transformer(
            depth=2, reversible=True,
            remat_policy="dots_with_no_batch_dims_saveable",
        )
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        variables = tfm_plain.init(jax.random.PRNGKey(1), x)
        np.testing.assert_allclose(
            np.asarray(tfm_plain.apply(variables, x)),
            np.asarray(tfm_pol.apply(variables, x)),
            atol=1e-6,
        )
        g1 = jax.grad(lambda p: (tfm_plain.apply({"params": p}, x) ** 2).sum())(
            variables["params"]
        )
        g2 = jax.grad(lambda p: (tfm_pol.apply({"params": p}, x) ** 2).sum())(
            variables["params"]
        )
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_noncausal_key_mask(self):
        tfm = make_transformer(causal=False, rotary_emb=False, image_fmap_size=None)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, 32))
        variables = tfm.init(jax.random.PRNGKey(1), x)
        mask = jnp.ones((2, SEQ), dtype=bool).at[:, -3:].set(False)
        out = tfm.apply(variables, x, key_mask=mask)
        # changing masked-out keys must not affect any output
        x2 = x.at[:, -1].add(100.0)
        out2 = tfm.apply(variables, x2, key_mask=mask)
        np.testing.assert_allclose(
            np.asarray(out[:, :-3]), np.asarray(out2[:, :-3]), atol=1e-5
        )


class TestRevnetExecutor:
    """True reversible executor (`reversible.py:57-127` semantics): the
    custom backward must reproduce plain autodiff exactly, since forward
    math is identical between impl='revnet' and impl='revnet_naive'."""

    def _pair(self, **kw):
        rev = make_transformer(reversible=True, reversible_impl="revnet", **kw)
        naive = make_transformer(reversible=True, reversible_impl="revnet_naive", **kw)
        return rev, naive

    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"shift_tokens": True, "sandwich_norm": True},
            {"attn_types": ("axial_row", "axial_col")},
            {"shared_attn_ids": (0, 0), "shared_ff_ids": (0, 0)},
        ],
    )
    def test_grads_match_autodiff(self, kw):
        rev, naive = self._pair(depth=2, **kw)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, 32))
        params = rev.init(jax.random.PRNGKey(1), x)

        def loss(p, mdl):
            return jnp.sum(mdl.apply(p, x) ** 2)

        out_rev = rev.apply(params, x)
        out_naive = naive.apply(params, x)
        np.testing.assert_allclose(out_rev, out_naive, atol=1e-5)

        g_rev = jax.grad(loss)(params, rev)
        g_naive = jax.grad(loss)(params, naive)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_rev), jax.tree_util.tree_leaves(g_naive)
        ):
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=1e-3)

    def test_reverse_model_order(self):
        rev, naive = self._pair(depth=3)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        params = rev.init(jax.random.PRNGKey(1), x)
        fwd = rev.apply(params, x)
        bwd_order = rev.apply(params, x, reverse_model=True)
        assert not np.allclose(fwd, bwd_order)
        np.testing.assert_allclose(
            bwd_order, naive.apply(params, x, reverse_model=True), atol=1e-5
        )

    def test_differs_from_sequential_function(self):
        # the revnet computes the two-stream function, not the residual stack
        rev, _ = self._pair(depth=2)
        seq = make_transformer(depth=2)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, SEQ, 32))
        params = rev.init(jax.random.PRNGKey(1), x)
        assert not np.allclose(rev.apply(params, x), seq.apply(params, x))
