"""Pallas flash-attention kernel vs the dense-masked oracle.

Mirrors the test the reference never had for its DeepSpeed CUDA block-sparse
kernel (`/root/reference/dalle_pytorch/attention.py:339-398`): every mask
pattern the framework uses is checked against `dense_attention` on the same
mask, forward and backward. Runs in Pallas interpret mode on CPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.ops.attention_core import dense_attention
from dalle_pytorch_tpu.ops.pallas_attention import (
    HAS_FORCE_TPU_INTERPRET,
    flash_attention,
    mask_block_layout,
)
from dalle_pytorch_tpu.ops.masks import (
    axial_static_mask,
    block_layout_to_token_mask,
    block_sparse_layout,
    causal_mask,
    conv_like_mask,
)

B, H, D = 2, 3, 32


def _qkv(n, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(B, H, n, D), dtype) for _ in range(3)
    )


def _dense(q, k, v, mask):
    return dense_attention(q, k, v, mask=jnp.asarray(mask)[None, None])


def test_causal_no_mask_matches_dense():
    n = 192
    q, k, v = _qkv(n)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = _dense(q, k, v, causal_mask(n))
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("pattern", ["axial_row", "axial_col", "conv", "sparse"])
def test_static_masks_match_dense(pattern):
    fmap, text = 8, 16
    n = text + fmap * fmap  # 80
    if pattern in ("axial_row", "axial_col"):
        mask = axial_static_mask(n - 1, fmap, axis=0 if pattern == "axial_row" else 1)
    elif pattern == "conv":
        mask = conv_like_mask(n - 1, fmap, kernel_size=3)
    else:
        layout = block_sparse_layout(n, block=16, global_block_indices=(0,), seed=3)
        mask = block_layout_to_token_mask(layout, 16)
    mask = mask[:n, :n] & causal_mask(n)
    q, k, v = _qkv(n, seed=1)
    out = flash_attention(q, k, v, mask=mask, causal=False, block_q=32, block_k=32)
    ref = _dense(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_ragged_seq_padding():
    n = 100  # not a multiple of the block size
    q, k, v = _qkv(n, seed=2)
    mask = causal_mask(n)
    out = flash_attention(q, k, v, mask=mask, causal=False, block_q=32, block_k=32)
    ref = _dense(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_rectangular_causal_nk_gt_nq():
    """n_k > n_q with causal=True: k blocks past the last q row are fully
    dead; the DMA-skip clamp must stay in range (regression: the dk/dv
    first-live-q index could point past the last q block, an out-of-bounds
    tile read) and dk/dv for those rows must be exactly zero."""
    n_q, n_k = 64, 160
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(B, H, n_q, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, n_k, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, n_k, D), jnp.float32)
    mask = np.arange(n_q)[:, None] >= np.arange(n_k)[None, :]

    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = _dense(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=32, block_k=32) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, mask=jnp.asarray(mask)[None, None]) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=5e-4)
    # fully-dead k rows (beyond the last q row) get exactly zero dk/dv
    assert np.all(np.asarray(gf[1])[:, :, n_q:, :] == 0)
    assert np.all(np.asarray(gf[2])[:, :, n_q:, :] == 0)


def test_gradients_match_dense_causal():
    n = 96
    q, k, v = _qkv(n, seed=3)
    mask = causal_mask(n)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=32, block_k=32) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_gradients_match_dense_masked_ragged():
    n = 72
    q, k, v = _qkv(n, seed=4)
    rng = np.random.RandomState(0)
    mask = causal_mask(n)
    mask &= rng.rand(n, n) > 0.3
    np.fill_diagonal(mask, True)  # keep every row non-empty

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, mask=mask, causal=False, block_q=32, block_k=32) ** 3).sum()

    def loss_dense(q, k, v):
        return (_dense(q, k, v, mask) ** 3).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_bf16_inputs():
    n = 64
    q, k, v = _qkv(n, seed=5, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    ref = _dense(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal_mask(n),
    )
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)


def test_empty_query_row_rejected():
    mask = causal_mask(64)
    mask[10, :] = False  # query 10 can attend to nothing
    q, k, v = _qkv(64, seed=6)
    with pytest.raises(ValueError, match="fully-masked query"):
        flash_attention(q, k, v, mask=mask, causal=False, block_q=32, block_k=32)


def test_flash_rejects_dynamic_key_mask():
    from dalle_pytorch_tpu.models.attention import Attention

    x = jnp.zeros((2, 16, 32))
    attn = Attention(dim=32, seq_len=16, heads=2, dim_head=16, attn_impl="flash")
    params = attn.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="key-padding"):
        attn.apply(params, x, key_mask=jnp.ones((2, 16), bool))


def test_block_layout_skips_empty_tiles():
    mask = np.zeros((64, 64), dtype=bool)
    mask[:, :16] = True  # every query attends only within the first k block
    _, layout = mask_block_layout(mask, 16, 16)
    assert layout.shape == (4, 4)
    assert (layout[:, 0] == 1).all() and layout.sum() == 4


def test_attention_module_flash_matches_dense():
    from dalle_pytorch_tpu.models.attention import Attention

    n, dim = 80, 64
    x = jnp.asarray(np.random.RandomState(7).randn(2, n, dim), jnp.float32)
    static = axial_static_mask(n - 1, 8, axis=0)[:n, :n]
    kw = dict(dim=dim, seq_len=n, heads=4, dim_head=16, causal=True, static_mask=static)
    dense_attn = Attention(**kw, attn_impl="dense")
    flash_attn = Attention(**kw, attn_impl="flash")
    params = dense_attn.init(jax.random.PRNGKey(0), x)
    out_d, _ = dense_attn.apply(params, x)
    out_f, _ = flash_attn.apply(params, x)
    np.testing.assert_allclose(out_f, out_d, atol=2e-5)


@pytest.mark.skipif(
    not HAS_FORCE_TPU_INTERPRET,
    reason="this jax has no pltpu.force_tpu_interpret_mode: the LIBRARY "
    "kernel cannot be interpreted on CPU (the in-repo kernels can and are "
    "tested above); lib_flash is TPU-hardware-only here",
)
class TestLibFlash:
    """jax library TPU flash kernel behind `lib_flash_attention` /
    attn_impl="lib_flash" (interpret mode on CPU)."""

    def test_matches_dense_causal(self):
        from dalle_pytorch_tpu.ops.pallas_attention import lib_flash_attention

        n = 256  # library kernel wants block-multiple seq lengths
        q, k, v = _qkv(n)
        out = lib_flash_attention(q, k, v, causal=True)
        ref = _dense(q, k, v, causal_mask(n))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grad_matches_dense(self):
        import jax.experimental.pallas.tpu as pltpu

        from dalle_pytorch_tpu.ops.pallas_attention import lib_flash_attention

        n = 256
        q, k, v = _qkv(n)

        def loss_lib(q):
            return lib_flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

        def loss_dense(q):
            return _dense(q, k, v, causal_mask(n)).astype(jnp.float32).sum()

        # the library kernel's custom-VJP backward traces its own
        # pallas_calls outside lib_flash_attention's internal interpret
        # guard, so on CPU the WHOLE grad must run under the interpret
        # context (on TPU none of this applies)
        with pltpu.force_tpu_interpret_mode():
            gl = jax.grad(loss_lib)(q)
        gd = jax.grad(loss_dense)(q)
        np.testing.assert_allclose(np.asarray(gl), np.asarray(gd), atol=5e-4)

    def test_attention_module_path(self):
        from dalle_pytorch_tpu.models.attention import Attention

        n = 256
        x = jnp.asarray(np.random.RandomState(0).randn(2, n, 64), jnp.float32)
        dense = Attention(dim=64, seq_len=n, heads=2, dim_head=32,
                          causal=True, attn_impl="dense")
        lib = Attention(dim=64, seq_len=n, heads=2, dim_head=32,
                        causal=True, attn_impl="lib_flash")
        params = dense.init(jax.random.PRNGKey(0), x)
        out_d, _ = dense.apply(params, x)
        out_l, _ = lib.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(out_d), np.asarray(out_l), atol=2e-4
        )

    def test_rejects_masks(self):
        from dalle_pytorch_tpu.models.attention import Attention

        n = 256  # library kernel needs seq >= its 128 block size
        x = jnp.zeros((1, n, 32))
        attn = Attention(dim=32, seq_len=n, heads=2, dim_head=16,
                         causal=True, attn_impl="lib_flash")
        params = attn.init(jax.random.PRNGKey(0), x)
        with pytest.raises(ValueError, match="lib_flash"):
            attn.apply(params, x, key_mask=jnp.ones((1, n), bool))
