"""Repo hygiene: no debugger artifacts in the shipped package.

The reference codebase shipped live import-time breakpoints — `import ipdb;
st()` at module scope (SURVEY.md §0) — which turn any import into a hung
process. This check used to be a regex scan; it is now a thin shim over
tracelint's TL006 rule (`dalle_pytorch_tpu/analysis/`), which parses the
AST instead of pattern-matching lines: strings and comments mentioning
`breakpoint()` no longer need carve-outs, and `.set_trace()` is covered
too. The suite still fails with the same SURVEY.md §0 message.
"""

from pathlib import Path

from dalle_pytorch_tpu.analysis import lint_paths

PACKAGE = Path(__file__).resolve().parent.parent / "dalle_pytorch_tpu"


def test_no_debugger_artifacts_in_package():
    assert PACKAGE.is_dir(), f"package dir moved? {PACKAGE}"
    result = lint_paths([PACKAGE], select={"TL006"})
    assert result.clean, (
        "debugger artifacts in shipped code (the reference repo's "
        "import-time-breakpoint regression, SURVEY.md §0):\n"
        + "\n".join(f.render() for f in result.findings)
    )
