"""Repo hygiene: no debugger artifacts in the shipped package.

The reference codebase shipped live import-time breakpoints — `import ipdb;
st()` at module scope (SURVEY.md §0) — which turn any import into a hung
process. This lint fails the suite if `ipdb`, `breakpoint()`, or the
`st()` alias appears anywhere under `dalle_pytorch_tpu/`, so the same
regression can never land here.
"""

import re
from pathlib import Path

PACKAGE = Path(__file__).resolve().parent.parent / "dalle_pytorch_tpu"

# \b keeps identifiers like `list(` or `self.first(` from matching st(
PATTERNS = {
    "ipdb import": re.compile(r"\bipdb\b"),
    "breakpoint() call": re.compile(r"\bbreakpoint\s*\("),
    "st() debugger alias": re.compile(r"\bst\s*\(\s*\)"),
}


def test_no_debugger_artifacts_in_package():
    assert PACKAGE.is_dir(), f"package dir moved? {PACKAGE}"
    offenders = []
    for path in sorted(PACKAGE.rglob("*.py")):
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1
        ):
            stripped = line.split("#", 1)[0]  # commented-out code is noise
            for what, pat in PATTERNS.items():
                if pat.search(stripped):
                    offenders.append(
                        f"{path.relative_to(PACKAGE.parent)}:{lineno}: "
                        f"{what}: {line.strip()}"
                    )
    assert not offenders, (
        "debugger artifacts in shipped code (the reference repo's "
        "import-time-breakpoint regression, SURVEY.md §0):\n"
        + "\n".join(offenders)
    )
