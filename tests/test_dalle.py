import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.dalle import (
    DALLE,
    generate_images,
    generate_texts,
    forward_with_cond_scale,
)

TEXT_SEQ = 6
FMAP = 3
IMG_SEQ = FMAP * FMAP
NUM_TEXT = 20
NUM_IMG = 16


def make_dalle(**kw):
    defaults = dict(
        dim=32,
        depth=2,
        num_image_tokens=NUM_IMG,
        image_fmap_size=FMAP,
        num_text_tokens=NUM_TEXT,
        text_seq_len=TEXT_SEQ,
        heads=2,
        dim_head=8,
        shift_tokens=False,
        rotary_emb=True,
    )
    defaults.update(kw)
    return DALLE(**defaults)


@pytest.fixture
def batch():
    rng = jax.random.PRNGKey(0)
    text = jax.random.randint(rng, (2, TEXT_SEQ), 1, NUM_TEXT)
    text = text.at[:, -2:].set(0)  # trailing padding
    image = jax.random.randint(jax.random.PRNGKey(1), (2, IMG_SEQ), 0, NUM_IMG)
    return text, image


def init_vars(model, text, image):
    return model.init(jax.random.PRNGKey(42), text, image)


class TestDALLEForward:
    def test_logits_shape_and_mask(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        logits = model.apply(variables, text, image)
        total_seq = TEXT_SEQ + IMG_SEQ
        total_tokens = NUM_TEXT + TEXT_SEQ + NUM_IMG
        assert logits.shape == (2, total_seq, total_tokens)

        arr = np.asarray(logits)
        text_vocab = NUM_TEXT + TEXT_SEQ
        # text positions may only produce text tokens
        assert (arr[:, : TEXT_SEQ, text_vocab:] < -1e30).all()
        assert np.isfinite(arr[:, : TEXT_SEQ, :text_vocab]).all()
        # image positions may only produce image tokens
        assert (arr[:, TEXT_SEQ:, :text_vocab] < -1e30).all()
        assert np.isfinite(arr[:, TEXT_SEQ:, text_vocab:]).all()

    def test_inverse_mask_rotated(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        logits = model.apply(variables, text, image, inverse_mapping=True)
        arr = np.asarray(logits)
        text_vocab = NUM_TEXT + TEXT_SEQ
        # image occupies the FRONT of the sequence in inverse mode
        assert (arr[:, :IMG_SEQ, :text_vocab] < -1e30).all()
        assert (arr[:, IMG_SEQ:, text_vocab:] < -1e30).all()

    def test_loss_modes(self, batch):
        """forward / forward_forward / forward_reverse_partial objectives."""
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)

        loss, acc = model.apply(variables, text, image, return_loss=True)
        assert np.isfinite(float(loss)) and acc is None

        inv_loss, inv_acc = model.apply(
            variables, text, image, return_loss=True, inverse_mapping=True
        )
        assert np.isfinite(float(inv_loss))
        assert 0.0 <= float(inv_acc) <= 1.0

        rev_loss, _ = model.apply(
            variables, text, image, return_loss=True,
            inverse_mapping=True, reverse_model=True,
        )
        assert np.isfinite(float(rev_loss))
        assert float(rev_loss) != float(inv_loss)

    def test_grads_flow(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)

        def loss_fn(params):
            loss, _ = model.apply({"params": params}, text, image, return_loss=True)
            return loss

        grads = jax.grad(loss_fn)(variables["params"])
        total = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads))
        assert np.isfinite(total) and total > 0

    def test_unique_pad_tokens_distinguish_positions(self, batch):
        """Zero-padding at different positions embeds differently (`:606-609`)."""
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        t1 = jnp.zeros((1, TEXT_SEQ), jnp.int32).at[0, 0].set(5)
        t2 = jnp.zeros((1, TEXT_SEQ), jnp.int32).at[0, 1].set(5)
        l1 = model.apply(variables, t1, image[:1])
        l2 = model.apply(variables, t2, image[:1])
        assert not np.allclose(np.asarray(l1), np.asarray(l2))

    def test_feature_flag_matrix(self, batch):
        text, image = batch
        for kw in (
            {"stable": True},
            {"sandwich_norm": True},
            {"shift_tokens": True},
            {"rotary_emb": False},
            {"share_input_output_emb": True},
            {"attn_types": ("full", "axial_row")},
            {"reversible": True},
        ):
            model = make_dalle(**kw)
            variables = init_vars(model, text, image)
            loss, _ = model.apply(variables, text, image, return_loss=True)
            assert np.isfinite(float(loss)), kw

    def test_null_cond_prob_drops_text(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        l_cond = model.apply(variables, text, image)
        l_null = model.apply(
            variables, text, image, null_cond_prob=1.0,
            rngs={"null_cond": jax.random.PRNGKey(0)},
        )
        assert not np.allclose(np.asarray(l_cond), np.asarray(l_null))
        # null-conditioning equals passing all-padding text
        l_zeros = model.apply(variables, jnp.zeros_like(text), image)
        np.testing.assert_allclose(np.asarray(l_null), np.asarray(l_zeros), atol=1e-5)


class TestGeneration:
    def test_generate_images_tokens_in_range(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        toks = generate_images(
            model, variables, jax.random.PRNGKey(0), text, filter_thres=0.9
        )
        assert toks.shape == (2, IMG_SEQ)
        arr = np.asarray(toks)
        assert (arr >= 0).all() and (arr < NUM_IMG).all()

    def test_generate_with_priming(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        toks = generate_images(
            model,
            variables,
            jax.random.PRNGKey(0),
            text,
            init_image_tokens=image,
            num_init_img_tokens=4,
        )
        np.testing.assert_array_equal(np.asarray(toks[:, :4]), np.asarray(image[:, :4]))

    def test_cond_scale_two_forward_blend(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        l1 = forward_with_cond_scale(model, variables, text, image, cond_scale=1.0)
        l3 = forward_with_cond_scale(model, variables, text, image, cond_scale=3.0)
        assert not np.allclose(np.asarray(l1), np.asarray(l3))

    def test_generate_texts(self, batch):
        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        out = generate_texts(
            model, variables, jax.random.PRNGKey(0), text, prefix_len=2
        )
        assert out.shape == (2, TEXT_SEQ)
        np.testing.assert_array_equal(np.asarray(out[:, :2]), np.asarray(text[:, :2]))
        arr = np.asarray(out)
        assert (arr >= 0).all() and (arr < NUM_TEXT + TEXT_SEQ).all()


class TestCachedDecode:
    """Cached decode must reproduce the uncached oracle exactly.

    This is the test seam for the reference's broken cached-mask path
    (`dalle_pytorch.py:669-671` `assert False`): we re-derive the semantics
    and pin them against the full re-forward."""

    def _teacher_forced_rows(self, model, variables, text, image):
        """Run prefill + per-token cached steps feeding `image`; collect the
        logits row for every image slot."""
        from dalle_pytorch_tpu.models.dalle import init_decode_cache, DALLE

        b = text.shape[0]
        row, cache = model.apply(
            variables, text, init_decode_cache(model, b, jnp.float32),
            method=DALLE.decode_prefill,
        )
        rows = [row]
        for i in range(IMG_SEQ - 1):
            row, cache = model.apply(
                variables, image[:, i], jnp.asarray(i), cache,
                method=DALLE.decode_image_step,
            )
            rows.append(row)
        return jnp.stack(rows, axis=1)  # [B, IMG_SEQ, V]

    @pytest.mark.parametrize(
        "kw",
        [
            dict(),
            dict(shift_tokens=True),
            dict(shift_tokens=True, attn_types=("full", "axial_row")),
            dict(rotary_emb=False, stable=True, sandwich_norm=True),
            dict(reversible=True, reversible_impl="revnet", shift_tokens=True),
        ],
        ids=["plain", "shift", "shift+axial", "posemb+stable+sandwich", "revnet"],
    )
    def test_cached_matches_full_forward(self, batch, kw):
        model = make_dalle(**kw)
        text, image = batch
        variables = init_vars(model, text, image)

        full = model.apply(variables, text, image)  # [B, total, V]
        oracle = full[:, TEXT_SEQ:]  # rows for image slots 0..IMG_SEQ-1
        cached = self._teacher_forced_rows(model, variables, text, image)

        # compare image-vocab columns only (text cols are -inf masked in the
        # full path; cached rows are masked later, at sampling)
        v0 = NUM_TEXT + TEXT_SEQ
        np.testing.assert_allclose(
            np.asarray(cached[..., v0:]),
            np.asarray(oracle[..., v0:]),
            rtol=1e-4,
            atol=1e-4,
        )

    def test_cached_generation_matches_uncached(self, batch):
        from dalle_pytorch_tpu.models.dalle import generate_images_cached

        model = make_dalle(shift_tokens=True)
        text, image = batch
        variables = init_vars(model, text, image)
        rng = jax.random.PRNGKey(7)
        slow = generate_images(model, variables, rng, text, filter_thres=0.9)
        fast = generate_images_cached(model, variables, rng, text, filter_thres=0.9)
        np.testing.assert_array_equal(np.asarray(slow), np.asarray(fast))

    def test_fused_pixel_sampler_matches_two_step(self, batch):
        """vae=/vae_params= fuses the dVAE pixel decode into the sampler
        program: tokens identical to the unfused sampler, pixels identical
        to decoding those tokens separately — one dispatch instead of
        two (the generate.py production path)."""
        from dalle_pytorch_tpu.models.dalle import generate_images_cached
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        # fmap 4 (not the suite's 3): the dVAE needs a power-of-2 image
        # size, fmap = image_size / 2^num_layers
        fmap = 4
        model = make_dalle(shift_tokens=True, image_fmap_size=fmap)
        text = batch[0]
        image = jnp.tile(batch[1], (1, 2))[:, : fmap * fmap] % NUM_IMG
        variables = init_vars(model, text, image)
        vae = DiscreteVAE(
            image_size=4 * fmap, num_layers=2, num_tokens=NUM_IMG,
            codebook_dim=16, hidden_dim=16,
        )
        vparams = jax.jit(vae.init)(
            jax.random.PRNGKey(5), jnp.zeros((1, 4 * fmap, 4 * fmap, 3))
        )["params"]

        rng = jax.random.PRNGKey(7)
        toks = generate_images_cached(model, variables, rng, text)
        ftoks, pixels = generate_images_cached(
            model, variables, rng, text, vae=vae, vae_params=vparams
        )
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(ftoks))
        want = vae.apply({"params": vparams}, toks, method=DiscreteVAE.decode)
        np.testing.assert_allclose(
            np.asarray(pixels), np.asarray(want), atol=1e-6
        )
        assert pixels.shape == (text.shape[0], 4 * fmap, 4 * fmap, 3)

    def test_cached_generation_priming_and_guidance(self, batch):
        from dalle_pytorch_tpu.models.dalle import generate_images_cached

        model = make_dalle()
        text, image = batch
        variables = init_vars(model, text, image)
        toks = generate_images_cached(
            model,
            variables,
            jax.random.PRNGKey(0),
            text,
            cond_scale=2.0,
            init_image_tokens=image,
            num_init_img_tokens=4,
        )
        arr = np.asarray(toks)
        np.testing.assert_array_equal(arr[:, :4], np.asarray(image[:, :4]))
        assert (arr >= 0).all() and (arr < NUM_IMG).all()


class TestFusedCE:
    """Vocab-chunked CE (ops/losses.py) must match the dense loss path
    bit-for-bit in semantics: same loss, same grads."""

    @staticmethod
    def _assert_grad_parity(g_dense, g_fused, atol=2e-5, label=""):
        flat_d = {jax.tree_util.keystr(k): v
                  for k, v in jax.tree_util.tree_leaves_with_path(g_dense)}
        flat_f = {jax.tree_util.keystr(k): v
                  for k, v in jax.tree_util.tree_leaves_with_path(g_fused)}
        assert flat_d.keys() == flat_f.keys()
        for k in flat_d:
            np.testing.assert_allclose(
                np.asarray(flat_d[k]), np.asarray(flat_f[k]), atol=atol,
                err_msg=f"{label}grad mismatch at {k}",
            )

    def _pair(self, share_emb=False):
        kw = dict(
            dim=32, depth=2, heads=2, dim_head=16, num_image_tokens=48,
            image_fmap_size=4, num_text_tokens=60, text_seq_len=12,
            shift_tokens=True, rotary_emb=True,
            share_input_output_emb=share_emb,
        )
        return DALLE(fused_ce=False, **kw), DALLE(fused_ce=True, **kw)

    @pytest.mark.slow  # ~22 s/param: dense + fused grads compile two big
    # programs (tier-1 budget); TestFusedCEMultiStep keeps fused-CE
    # training covered in the fast tier
    @pytest.mark.parametrize("share_emb", [False, True])
    def test_loss_and_grad_parity(self, share_emb):
        dense, fused = self._pair(share_emb)
        rng = jax.random.PRNGKey(0)
        text = jax.random.randint(rng, (3, 12), 1, 60)
        image = jax.random.randint(rng, (3, 16), 0, 48)
        params = dense.init(rng, text, image)["params"]

        def loss_of(model):
            def f(p):
                loss, _ = model.apply(
                    {"params": p}, text, image, return_loss=True
                )
                return loss
            return f

        l_dense = loss_of(dense)(params)
        l_fused = loss_of(fused)(params)
        np.testing.assert_allclose(
            float(l_dense), float(l_fused), rtol=2e-5,
            err_msg="fused CE loss diverged from dense path",
        )
        g_dense = jax.grad(loss_of(dense))(params)
        g_fused = jax.grad(loss_of(fused))(params)
        self._assert_grad_parity(g_dense, g_fused)

    @pytest.mark.slow  # ~17 s/param: same two-program compile as above
    # for the inverse path (tier-1 budget)
    @pytest.mark.parametrize("share_emb", [False, True])
    def test_fused_inverse_parity(self, share_emb):
        """The fused inverse path (vocab-chunked CE + [B,3,V] dense
        accuracy block) must match the dense inverse path: same loss,
        same 3-token accuracy, same grads."""
        dense, fused = self._pair(share_emb)
        rng = jax.random.PRNGKey(0)
        text = jax.random.randint(rng, (2, 12), 1, 60)
        image = jax.random.randint(rng, (2, 16), 0, 48)
        params = dense.init(rng, text, image)["params"]

        def loss_of(model):
            def f(p):
                loss, _ = model.apply(
                    {"params": p}, text, image, return_loss=True,
                    inverse_mapping=True,
                )
                return loss
            return f

        ld, accd = dense.apply(
            {"params": params}, text, image, return_loss=True, inverse_mapping=True
        )
        lf, accf = fused.apply(
            {"params": params}, text, image, return_loss=True, inverse_mapping=True
        )
        np.testing.assert_allclose(float(ld), float(lf), rtol=2e-5)
        np.testing.assert_allclose(float(accd), float(accf), rtol=1e-6)

        g_dense = jax.grad(loss_of(dense))(params)
        g_fused = jax.grad(loss_of(fused))(params)
        self._assert_grad_parity(g_dense, g_fused, label="inverse ")

    def test_chunk_boundary_labels(self):
        """Labels on chunk edges (0, chunk-1, chunk, V-1) gather correctly."""
        from dalle_pytorch_tpu.ops.losses import chunked_masked_ce
        import jax.numpy as jnp

        B, N, D, V, chunk = 2, 6, 8, 10, 4  # V not a multiple of chunk
        rng = jax.random.PRNGKey(0)
        h = jax.random.normal(rng, (B, N, D))
        kernel = jax.random.normal(jax.random.PRNGKey(1), (D, V)) * 0.3
        bias = jax.random.normal(jax.random.PRNGKey(2), (V,)) * 0.1
        row_is_text = jnp.array([True] * 3 + [False] * 3)
        num_text_vocab = 5
        labels = jnp.array([[0, 3, 4, 5, 8, 9], [1, 2, 0, 7, 6, 5]])

        got = chunked_masked_ce(
            h, kernel, bias, labels,
            row_is_text=row_is_text, num_text_vocab=num_text_vocab,
            chunk=chunk,
        )
        # dense oracle
        logits = (h @ kernel + bias).astype(jnp.float32)
        vocab_is_text = jnp.arange(V) < num_text_vocab
        allowed = row_is_text[:, None] == vocab_is_text[None, :]
        logits = jnp.where(allowed[None], logits, -1e30)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        want = logz - gold
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


class TestFusedCEMultiStep:
    """Regression: jax 0.9's jit C++ fastpath drops hoisted constant
    arguments from call 3 onward ("Execution supplied N buffers but
    compiled program expected M"). A module-level `jnp.float32` constant
    in ops/losses.py triggered it for every fused-CE train step — parity
    tests (1-2 calls) never saw it; any real training run crashed at
    step 3. Pin: 5 donated jitted steps must survive."""

    @pytest.mark.parametrize("executor", ["unrolled", "scan"])
    def test_five_donated_steps(self, executor):
        from dalle_pytorch_tpu.training import (
            TrainState, make_optimizer, make_dalle_train_step,
        )

        model = DALLE(
            dim=32, depth=2, heads=2, dim_head=16, num_image_tokens=48,
            image_fmap_size=4, num_text_tokens=60, text_seq_len=12,
            shift_tokens=True, rotary_emb=True,
            reversible=True, reversible_impl="remat",
            remat_policy="dots_with_no_batch_dims_saveable", fused_ce=True,
            executor=executor,
        )
        text = jnp.ones((2, 12), jnp.int32)
        tokens = jnp.zeros((2, 16), jnp.int32)
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0), text, tokens
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=make_optimizer(3e-4, clip_grad_norm=0.5),
        )
        step = jax.jit(make_dalle_train_step(model), donate_argnums=0)
        batch = {"text": text, "image_tokens": tokens}
        rng = jax.random.PRNGKey(1)
        losses = []
        for _ in range(5):
            rng, r = jax.random.split(rng)
            state, m = step(state, batch, r)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]


class TestCombinedPerfFeatures:
    """The bench's fastest profile stacks flash attention + selective remat
    + fused CE; their composition must agree with the plain model."""

    def test_flash_policy_fusedce_matches_baseline(self):
        kw = dict(
            dim=32, depth=2, heads=2, dim_head=16, num_image_tokens=48,
            image_fmap_size=4, num_text_tokens=60, text_seq_len=12,
            shift_tokens=True, rotary_emb=True,
        )
        base = DALLE(**kw)
        fast = DALLE(
            attn_impl="flash", reversible=True, reversible_impl="remat",
            remat_policy="dots_with_no_batch_dims_saveable", fused_ce=True,
            **kw,
        )
        rng = jax.random.PRNGKey(0)
        text = jax.random.randint(rng, (2, 12), 1, 60)
        image = jax.random.randint(rng, (2, 16), 0, 48)
        params = base.init(rng, text, image)["params"]

        def loss(model, p):
            l, _ = model.apply({"params": p}, text, image, return_loss=True)
            return l

        l_base = float(loss(base, params))
        l_fast = float(loss(fast, params))
        np.testing.assert_allclose(l_base, l_fast, rtol=5e-3)

        g_base = jax.grad(lambda p: loss(base, p))(params)
        g_fast = jax.grad(lambda p: loss(fast, p))(params)
        for a, b in zip(jax.tree.leaves(g_base), jax.tree.leaves(g_fast)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-3,
            )
