"""CLIP model tests: encoders, InfoNCE loss, rerank, checkpoint roundtrip.

Mirrors the surface of the reference `CLIP`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:274-350`) and its use as a
generation reranker (`dalle_pytorch.py:569-571`).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.clip import CLIP, clip_scores, rerank


def tiny_clip(**kw):
    defaults = dict(
        dim_text=32,
        dim_image=32,
        dim_latent=16,
        num_text_tokens=50,
        text_enc_depth=1,
        text_seq_len=8,
        text_heads=2,
        visual_enc_depth=1,
        visual_heads=2,
        visual_image_size=16,
        visual_patch_size=8,
    )
    defaults.update(kw)
    return CLIP(**defaults)


def init_clip(clip, b=3):
    text = jnp.ones((b, clip.text_seq_len), jnp.int32)
    image = jnp.zeros((b, 16, 16, 3), jnp.float32)
    variables = clip.init(jax.random.PRNGKey(0), text, image)
    return variables, text, image


class TestCLIP:
    def test_scores_shape_and_finite(self):
        clip = tiny_clip()
        variables, text, image = init_clip(clip)
        scores = clip.apply(variables, text, image)
        assert scores.shape == (3,)
        assert np.all(np.isfinite(np.asarray(scores)))

    @pytest.mark.slow  # ~26 s: the CLIP grad compile (tier-1 budget);
    # forward coverage stays fast via test_scores_shape_and_finite
    def test_loss_scalar_and_grad(self):
        clip = tiny_clip()
        variables, text, image = init_clip(clip)
        key = jax.random.PRNGKey(1)
        image = jax.random.uniform(key, image.shape)

        def loss_fn(v):
            return clip.apply(v, text, image, return_loss=True)

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        assert loss.shape == ()
        assert np.isfinite(float(loss))
        assert all(
            np.all(np.isfinite(np.asarray(g)))
            for g in jax.tree_util.tree_leaves(grads)
        )

    def test_text_mask_changes_latent(self):
        clip = tiny_clip()
        variables, text, image = init_clip(clip)
        mask = jnp.asarray(np.array([[1] * 4 + [0] * 4] * 3, dtype=bool))
        s_masked = clip.apply(variables, text, image, text_mask=mask)
        s_plain = clip.apply(variables, text, image)
        assert not np.allclose(np.asarray(s_masked), np.asarray(s_plain))

    def test_rerank_orders_by_score(self):
        clip = tiny_clip()
        variables, text, _ = init_clip(clip, b=4)
        images = jax.random.uniform(jax.random.PRNGKey(2), (4, 16, 16, 3))
        sorted_imgs, scores, order = rerank(clip, variables, text[:1], images)
        assert sorted_imgs.shape == images.shape
        s = np.asarray(scores)
        assert np.all(s[:-1] >= s[1:])  # descending
        raw = np.asarray(
            clip_scores(clip, variables, jnp.repeat(text[:1], 4, axis=0), images)
        )
        np.testing.assert_allclose(np.sort(raw)[::-1], s, rtol=1e-6)

    def test_checkpoint_roundtrip(self, tmp_path):
        from dalle_pytorch_tpu.training.pipeline import (
            save_clip_checkpoint,
            load_clip_checkpoint,
        )

        clip = tiny_clip()
        variables, text, image = init_clip(clip)
        path = str(tmp_path / "clip.npz")
        save_clip_checkpoint(path, clip, variables["params"])
        clip2, params2 = load_clip_checkpoint(path)
        assert clip2.text_seq_len == clip.text_seq_len
        s1 = clip.apply(variables, text, image)
        s2 = clip2.apply({"params": params2}, text, image)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)
