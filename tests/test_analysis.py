"""tracelint: rule-pack coverage over the fixture corpus, suppression and
baseline workflows, the CLI contract, and the package-stays-clean gate.

Each rule TL001-TL006 is pinned by a positive fixture it must catch and a
negative fixture it must ignore (tests/lint_fixtures/). The package gate
at the bottom is the acceptance criterion: the shipped baseline is empty
and `python -m dalle_pytorch_tpu.analysis` exits 0 over the package.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from dalle_pytorch_tpu.analysis import PACKAGE_DIR, lint_paths
from dalle_pytorch_tpu.analysis.baseline import (
    DEFAULT_BASELINE,
    load_baseline,
    write_baseline,
)

FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def codes(result):
    return [f.rule for f in result.findings]


# ------------------------------------------------------------ rule corpus


class TestRuleCorpus:
    """Every rule: the positive fixture trips it, the negative doesn't."""

    @pytest.mark.parametrize(
        "fixture, code, expected",
        [
            ("tl001_pos.py", "TL001", 5),
            ("tl001_xproc_pos.py", "TL001", 3),
            ("tl002_pos.py", "TL002", 7),
            ("tl002_xproc_pos.py", "TL002", 2),
            ("tl003_pos.py", "TL003", 3),
            ("tl004_pos.py", "TL004", 3),
            ("models/tl005_pos.py", "TL005", 3),
            ("tl006_pos.py", "TL006", 4),
            ("tl007_pos.py", "TL007", 3),
            ("tl007_bitmap_pos.py", "TL007", 2),
            ("tl008_pos.py", "TL008", 3),
            ("tl008_paged_pos.py", "TL008", 3),
            ("tl009_pos.py", "TL009", 3),
            ("serving/tl010_pos.py", "TL010", 3),
            ("serving/tl011_pos.py", "TL011", 3),
            ("serving/tl012_pos.py", "TL012", 3),
            ("serving/tl022_pos.py", "TL022", 3),
        ],
    )
    def test_positive_fixture_caught(self, fixture, code, expected):
        result = lint_paths([FIXTURES / fixture])
        got = codes(result)
        assert got.count(code) == expected, (
            f"{fixture}: expected {expected} {code} findings, got {got}"
        )
        assert all(c == code for c in got), (
            f"{fixture}: unexpected extra findings {got}"
        )

    @pytest.mark.parametrize(
        "fixture",
        [
            "tl001_neg.py",
            "tl001_xproc_neg.py",
            "tl002_neg.py",
            "tl002_xproc_neg.py",
            "tl003_neg.py",
            "tl004_neg.py",
            "models/tl005_neg.py",
            "tl006_neg.py",
            "tl007_neg.py",
            "tl007_bitmap_neg.py",
            "tl008_neg.py",
            "tl008_paged_neg.py",
            "tl009_neg.py",
            "serving/tl010_neg.py",
            "serving/tl011_neg.py",
            "serving/tl012_neg.py",
            "serving/tl022_neg.py",
        ],
    )
    def test_negative_fixture_clean(self, fixture):
        result = lint_paths([FIXTURES / fixture])
        assert result.clean, (
            f"{fixture} should be clean, got: "
            + "; ".join(f.render() for f in result.findings)
        )

    def test_tl005_scoped_to_models_and_ops(self, tmp_path):
        """The same dtype-less constructor outside models/ or ops/ is out
        of the precision-discipline scope."""
        f = tmp_path / "elsewhere.py"
        f.write_text(
            "import jax.numpy as jnp\n\ndef g(n):\n    return jnp.zeros(n)\n"
        )
        assert lint_paths([f]).clean

    def test_tl010_scoped_to_serving(self, tmp_path):
        """The same hot retry loop outside serving/ is out of scope —
        training scripts and tooling loop under different contracts."""
        src = (
            "def f(dispatch, log):\n"
            "    while True:\n"
            "        try:\n"
            "            dispatch()\n"
            "        except Exception as exc:\n"
            "            log(exc)\n"
            "            continue\n"
        )
        outside = tmp_path / "elsewhere.py"
        outside.write_text(src)
        assert lint_paths([outside]).clean
        serving = tmp_path / "serving"
        serving.mkdir()
        inside = serving / "loops.py"
        inside.write_text(src)
        assert codes(lint_paths([inside])) == ["TL010"]

    def test_tl011_scoped_to_serving(self, tmp_path):
        """The same unregistered jit outside serving/ is out of scope —
        models/ops build programs through their own cached builders."""
        src = (
            "import jax\n\n"
            "def g(x):\n"
            "    return jax.jit(lambda y: y)(x)\n"
        )
        outside = tmp_path / "elsewhere.py"
        outside.write_text(src)
        assert lint_paths([outside]).clean
        serving = tmp_path / "serving"
        serving.mkdir()
        inside = serving / "prog.py"
        inside.write_text(src)
        assert codes(lint_paths([inside])) == ["TL011"]

    def test_tl011_ladder_handle_reference_covers(self, tmp_path):
        """A jit assigned to a handle that ANY ladder-named function
        references is registered (the engine.py `_decode_pixels_jit` /
        `_capture_decode_pixels_cost` idiom); dropping the ladder
        function flips it to a finding."""
        serving = tmp_path / "serving"
        serving.mkdir()
        covered = (
            "import jax\n\n"
            "class E:\n"
            "    def build(self):\n"
            "        self._p = jax.jit(lambda x: x)\n"
            "    def _capture_cost_of_p(self):\n"
            "        return self._p\n"
        )
        f = serving / "covered.py"
        f.write_text(covered)
        assert lint_paths([f]).clean
        g = serving / "uncovered.py"
        g.write_text(
            "import jax\n\n"
            "class E:\n"
            "    def build(self):\n"
            "        self._p = jax.jit(lambda x: x)\n"
        )
        assert codes(lint_paths([g])) == ["TL011"]

    def test_tl012_scoped_to_serving(self, tmp_path):
        """The same unguarded snapshot loop outside serving/ is out of
        scope — only the serving worker runs a chunk loop."""
        src = (
            "def f(engine, buf):\n"
            "    while True:\n"
            "        buf.append(engine.snapshot_rows(range(4)))\n"
        )
        outside = tmp_path / "elsewhere.py"
        outside.write_text(src)
        assert lint_paths([outside]).clean
        serving = tmp_path / "serving"
        serving.mkdir()
        inside = serving / "loops.py"
        inside.write_text(src)
        assert codes(lint_paths([inside])) == ["TL012"]

    def test_tl012_nested_while_counts_once(self, tmp_path):
        """An unguarded snapshot in a nested while is ONE finding (the
        outer loop's scan descends; the inner loop gets no second
        visit), and an outer boundary guard covers the inner loop."""
        serving = tmp_path / "serving"
        serving.mkdir()
        f = serving / "nested.py"
        f.write_text(
            "def f(self):\n"
            "    while True:\n"
            "        while self.more:\n"
            "            bad = self.engine.snapshot_rows(range(4))\n"
        )
        assert codes(lint_paths([f])) == ["TL012"]
        g = serving / "nested_guarded.py"
        g.write_text(
            "def f(self):\n"
            "    while True:\n"
            "        if self.beacon_due():\n"
            "            while self.more:\n"
            "                ok = self.engine.snapshot_rows(range(4))\n"
        )
        assert lint_paths([g]).clean

    def test_tl012_else_of_guard_not_covered(self, tmp_path):
        """The else branch of a boundary guard is NOT at the boundary:
        a snapshot there still fires."""
        serving = tmp_path / "serving"
        serving.mkdir()
        f = serving / "worker.py"
        f.write_text(
            "def f(self):\n"
            "    while True:\n"
            "        if self.chunk_boundary():\n"
            "            ok = self.engine.snapshot_rows(range(4))\n"
            "        else:\n"
            "            bad = self.engine.snapshot_rows(range(4))\n"
        )
        assert codes(lint_paths([f])) == ["TL012"]

    def test_tl010_backoff_in_loop_body_counts(self, tmp_path):
        """The backoff/budget call may live anywhere in the loop, not
        just the handler — `sleep` before the try is still discipline."""
        serving = tmp_path / "serving"
        serving.mkdir()
        f = serving / "loops.py"
        f.write_text(
            "import time\n\n"
            "def f(dispatch, log):\n"
            "    while True:\n"
            "        time.sleep(0.2)\n"
            "        try:\n"
            "            dispatch()\n"
            "        except Exception as exc:\n"
            "            log(exc)\n"
        )
        assert lint_paths([f]).clean

    def test_tl006_message_points_at_survey(self):
        result = lint_paths([FIXTURES / "tl006_pos.py"])
        assert all("SURVEY.md" in f.message for f in result.findings)

    def test_tl007_size_heuristic_boundary(self, tmp_path):
        """The element-count threshold separates signal from noise: one
        element under MIN_ELEMENTS is silent, at the threshold it fires."""
        from dalle_pytorch_tpu.analysis.rules import ScanConstUploadRule

        n = ScanConstUploadRule.MIN_ELEMENTS
        template = textwrap.dedent(
            """\
            import numpy as np
            import jax.numpy as jnp
            from jax import lax

            def caller(xs):
                def body(carry, x):
                    t = jnp.asarray(np.arange({count}))
                    return carry + t[0], x

                return lax.scan(body, 0.0, xs)
            """
        )
        under = tmp_path / "under.py"
        under.write_text(template.format(count=n - 1))
        assert lint_paths([under]).clean
        at = tmp_path / "at.py"
        at.write_text(template.format(count=n))
        assert codes(lint_paths([at])) == ["TL007"]

    def test_tl008_axis_vocab_in_lockstep_with_mesh(self):
        """The rule's hardcoded make_mesh vocabulary (the linter never
        imports jax) must track parallel/mesh.py's MESH_AXES — a renamed
        axis would silently rot the factory resolution."""
        from dalle_pytorch_tpu.analysis.rules import _MAKE_MESH_AXES
        from dalle_pytorch_tpu.parallel.mesh import MESH_AXES

        assert tuple(_MAKE_MESH_AXES) == tuple(MESH_AXES)

    def test_tl008_factory_and_inline_mesh_resolution(self, tmp_path):
        """make_mesh-built meshes resolve to the 4-axis vocabulary; an
        inline Mesh(...) constructor resolves without a name binding."""
        f = tmp_path / "factory.py"
        f.write_text(textwrap.dedent(
            """\
            import numpy as np
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            from dalle_pytorch_tpu.parallel.mesh import make_mesh

            m = make_mesh(tp=2)
            assert DIM % 2 == 0  # divisibility asserted: keeps TL020 out
            bad = NamedSharding(m, P("model"))
            also_bad = NamedSharding(
                Mesh(np.asarray(jax.devices()), ("x",)), P("y")
            )
            fine = NamedSharding(m, P("tp", "fsdp"))
            """
        ))
        result = lint_paths([f])
        assert codes(result) == ["TL008", "TL008"]
        assert "'model'" in result.findings[0].message

    def test_tl009_finally_placement_is_decisive(self, tmp_path):
        """The same begin/work/end sequence flips clean<->finding on
        exactly one change: whether the end is exception-reachable."""
        template = textwrap.dedent(
            """\
            def handler(trace, work):
                span = trace.begin("respond")
                {shape}
            """
        )
        leaky = tmp_path / "leaky.py"
        leaky.write_text(template.format(shape="work()\n    trace.end(span)"))
        assert codes(lint_paths([leaky])) == ["TL009"]
        safe = tmp_path / "safe.py"
        safe.write_text(template.format(
            shape="try:\n        work()\n    finally:\n"
            "        trace.end(span)"
        ))
        assert lint_paths([safe]).clean

    def test_tl009_receiver_must_name_a_trace(self, tmp_path):
        """Unrelated `.begin()` APIs (db cursors, matchers) are out of
        scope — the receiver heuristic keeps the rule quiet there."""
        f = tmp_path / "cursor.py"
        f.write_text(textwrap.dedent(
            """\
            def txn(db, work):
                handle = db.begin("rw")
                work()
                db.end(handle)
            """
        ))
        assert lint_paths([f]).clean


# --------------------------------------------------------- severity tiers


WARNING_ONLY = textwrap.dedent(
    """\
    import jax

    class Engine:
        # tracelint: hotloop
        def step(self):
            return jax.device_get(self._state)
    """
)


class TestSeverityTiers:
    """TL002 splits 'sync under tracing' (error — always a bug) from
    'sync in a hotloop-marked loop' (warning tier, its own exit-code
    bit: 1 errors, 4 warnings, 5 both; 2 stays usage errors)."""

    def test_tl002_fixture_splits_by_severity(self):
        result = lint_paths([FIXTURES / "tl002_pos.py"])
        assert len(result.errors) == 4, [f.render() for f in result.errors]
        assert len(result.warnings) == 3
        assert all(f.rule == "TL002" for f in result.warnings)
        assert all("hot loop" in f.message for f in result.warnings)
        # warnings are findings: the package gate stays strict
        assert not result.clean

    def test_warning_only_exit_bit(self, tmp_path):
        from dalle_pytorch_tpu.analysis import main

        f = tmp_path / "hotloop_only.py"
        f.write_text(WARNING_ONLY)
        assert main([str(f)]) == 4

    def test_error_and_warning_exit_bits_compose(self):
        from dalle_pytorch_tpu.analysis import main

        assert main([str(FIXTURES / "tl002_pos.py")]) == 5
        # error-only fixtures keep the historical exit 1
        assert main([str(FIXTURES / "tl001_pos.py")]) == 1

    def test_warning_severity_in_json_and_text(self, tmp_path, capsys):
        from dalle_pytorch_tpu.analysis import main

        f = tmp_path / "hotloop_only.py"
        f.write_text(WARNING_ONLY)
        main([str(f), "--format", "json"])
        payload = json.loads(capsys.readouterr().out)
        assert [x["severity"] for x in payload["findings"]] == ["warning"]
        result = lint_paths([f])
        assert "TL002 warning:" in result.findings[0].render()
        assert "1 warning-tier" in __import__(
            "dalle_pytorch_tpu.analysis.lint", fromlist=["_render_text"]
        )._render_text(result)

    def test_github_format_warning_annotations(self, tmp_path, capsys):
        from dalle_pytorch_tpu.analysis import main

        f = tmp_path / "hotloop_only.py"
        f.write_text(WARNING_ONLY)
        rc = main([str(f), "--format", "github"])
        assert rc == 4
        out = capsys.readouterr().out
        assert "::warning file=" in out and "::error" not in out

    def test_reasoned_suppression_silences_warning_tier(self, tmp_path):
        f = tmp_path / "justified.py"
        f.write_text(WARNING_ONLY.replace(
            "jax.device_get(self._state)",
            "jax.device_get(self._state)  "
            "# tracelint: disable=TL002 -- fixture: designed boundary",
        ))
        result = lint_paths([f])
        assert result.clean and len(result.suppressed) == 1

    def test_severity_not_in_fingerprint(self, tmp_path):
        """Retiering a rule must never invalidate existing baselines."""
        f = tmp_path / "hotloop_only.py"
        f.write_text(WARNING_ONLY)
        (finding,) = lint_paths([f]).findings
        import dataclasses

        retiered = dataclasses.replace(finding, severity="error")
        assert retiered.fingerprint() == finding.fingerprint()


# ------------------------------------------------------------ suppressions


class TestSuppressions:
    def test_reasoned_suppression_hides_finding(self):
        result = lint_paths([FIXTURES / "suppressed_with_reason.py"])
        assert result.clean
        assert len(result.suppressed) == 1
        finding, sup = result.suppressed[0]
        assert finding.rule == "TL002"
        assert "reasoned suppression" in sup.reason

    def test_bare_suppression_rejected(self):
        result = lint_paths([FIXTURES / "suppressed_no_reason.py"])
        got = sorted(codes(result))
        assert got == ["TL000", "TL002"], got  # finding stays + TL000 on top

    def test_standalone_suppression_covers_next_line(self, tmp_path):
        f = tmp_path / "standalone.py"
        f.write_text(textwrap.dedent(
            """\
            import jax
            import numpy as np

            @jax.jit
            def g(x):
                # tracelint: disable=TL002 -- fixture: standalone line covers the next line
                return np.asarray(x)
            """
        ))
        result = lint_paths([f])
        assert result.clean and len(result.suppressed) == 1

    def test_tl006_has_no_opt_out(self, tmp_path):
        """A debugger artifact cannot be suppressed away — the regex scan
        this rule replaced had no opt-out, and neither does TL006."""
        f = tmp_path / "sneaky.py"
        f.write_text(
            "def g():\n"
            "    breakpoint()  # tracelint: disable=TL006 -- just debugging\n"
        )
        assert codes(lint_paths([f])) == ["TL006"]

    def test_suppression_is_per_rule(self, tmp_path):
        """A TL002 suppression does not silence a TL001 on the same line."""
        f = tmp_path / "wrongcode.py"
        f.write_text(textwrap.dedent(
            """\
            import jax

            @jax.jit
            def g(x):
                if x > 0:  # tracelint: disable=TL002 -- fixture: wrong rule code
                    return x
                return -x
            """
        ))
        assert codes(lint_paths([f])) == ["TL001"]


# ---------------------------------------------------- cross-file donation


def test_donation_contract_crosses_files(tmp_path):
    """The donation registry is package-wide: a wrapper whose builder tag
    lives in another file still poisons its argument at the call site —
    the serving-engine-vs-models/dalle.py split."""
    (tmp_path / "dispatch.py").write_text(textwrap.dedent(
        """\
        def _chunk_builder(model, key):
            def fn(state):
                return state
            return fn

        _chunk_builder._donate_argnums = (0,)

        def _jit_sample(builder, model, key, *args):
            return builder(model, key)(*args)

        def chunk(state):
            return _jit_sample(_chunk_builder, None, (), state)
        """
    ))
    (tmp_path / "caller.py").write_text(textwrap.dedent(
        """\
        from dispatch import chunk

        def serve(state):
            new = chunk(state)
            return state["img_pos"]
        """
    ))
    result = lint_paths([tmp_path])
    assert codes(result) == ["TL003"]
    assert result.findings[0].path.endswith("caller.py")


def test_donate_argnames_resolves_to_positions(tmp_path):
    """`jax.jit(f, donate_argnames=('state',))` donates by NAME; the
    registry resolves it through the wrapped def's parameter list."""
    f = tmp_path / "named.py"
    f.write_text(textwrap.dedent(
        """\
        import jax

        def _dispatch(params, state):
            return state

        g = jax.jit(_dispatch, donate_argnames=("state",))

        def serve(params, state):
            out = g(params, state)
            return out, state["row"]
        """
    ))
    assert codes(lint_paths([f])) == ["TL003"]


# ---------------------------------------------------------------- baseline


class TestBaseline:
    def test_grandfather_then_clean(self, tmp_path):
        """write-baseline grandfathers today's findings; the next run is
        clean; a NEW finding still fails."""
        target = FIXTURES / "tl006_pos.py"
        first = lint_paths([target])
        assert not first.clean

        bl = tmp_path / "baseline.json"
        write_baseline(bl, first.findings)
        prints = load_baseline(bl)
        again = lint_paths([target], baseline_fingerprints=prints)
        assert again.clean
        assert len(again.baselined) == len(first.findings)

        fresh = tmp_path / "fresh.py"
        fresh.write_text("import ipdb\n")
        third = lint_paths([target, fresh], baseline_fingerprints=prints)
        assert codes(third) == ["TL006"]
        assert third.findings[0].path.endswith("fresh.py")

    def test_fingerprint_survives_line_drift(self, tmp_path):
        """Fingerprints key on content, not line numbers: edits above a
        grandfathered finding don't resurrect it."""
        f = tmp_path / "drift.py"
        f.write_text("import ipdb\n")
        before = lint_paths([f]).findings[0].fingerprint()
        f.write_text("'''new docstring'''\nX = 1\n\nimport ipdb\n")
        after = lint_paths([f]).findings[0].fingerprint()
        assert before == after

    def test_duplicate_line_is_still_new(self, tmp_path):
        """Occurrence-aware fingerprints: adding a SECOND copy of an
        already-grandfathered line is a new finding, not a baseline hit
        (caught live while driving the CLI)."""
        f = tmp_path / "dup.py"
        f.write_text("def a():\n    breakpoint()\n")
        bl = tmp_path / "bl.json"
        write_baseline(bl, lint_paths([f]).findings)
        f.write_text(
            "def a():\n    breakpoint()\n\ndef b():\n    breakpoint()\n"
        )
        result = lint_paths([f], baseline_fingerprints=load_baseline(bl))
        assert codes(result) == ["TL006"]
        assert len(result.baselined) == 1

    def test_fingerprint_is_cwd_independent(self, tmp_path, monkeypatch):
        """Fingerprints key on root-relative paths, not the invocation
        directory — a baseline written from repo root still matches when
        CI lints from somewhere else."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text("import ipdb\n")
        monkeypatch.chdir(tmp_path)
        fp_here = lint_paths([pkg]).findings[0].fingerprint()
        monkeypatch.chdir(pkg)
        fp_there = lint_paths([pkg]).findings[0].fingerprint()
        assert fp_here == fp_there

    def test_write_baseline_needs_explicit_target_for_paths(self, tmp_path, capsys):
        """--write-baseline over explicit paths must not silently
        overwrite the shipped package baseline."""
        from dalle_pytorch_tpu.analysis import main

        f = tmp_path / "x.py"
        f.write_text("import ipdb\n")
        assert main([str(f), "--write-baseline"]) == 2
        assert "--baseline" in capsys.readouterr().err
        assert load_baseline(DEFAULT_BASELINE) == set()  # untouched

    def test_shipped_baseline_is_empty(self):
        """Acceptance: no grandfathered findings ship — every kept hazard
        carries an inline reasoned suppression instead."""
        assert load_baseline(DEFAULT_BASELINE) == set()


# --------------------------------------------------------------- CLI gate


class TestCLI:
    """Exit-code/format contracts via in-process `main(argv)` (same code
    path as the console script); one real subprocess pins the
    `python -m dalle_pytorch_tpu.analysis` module entry itself."""

    def test_module_entry_zero_on_clean_package(self):
        """The package itself lints clean through the real CLI — the
        zero-baseline acceptance criterion, enforced in-suite so a hazard
        can't land silently."""
        proc = subprocess.run(
            [sys.executable, "-m", "dalle_pytorch_tpu.analysis"],
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, (
            "package no longer lints clean:\n" + proc.stdout + proc.stderr
        )

    def test_nonzero_on_seeded_fixture_with_json(self, capsys):
        from dalle_pytorch_tpu.analysis import main

        rc = main([str(FIXTURES / "tl006_pos.py"), "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] and all(
            f["rule"] == "TL006" for f in payload["findings"]
        )

    def test_github_format_emits_error_annotations(self, capsys):
        """--format github: one ::error workflow command per finding, with
        the file/line properties CI needs to anchor the inline annotation,
        and the same nonzero exit as the other formats."""
        from dalle_pytorch_tpu.analysis import main

        rc = main([str(FIXTURES / "tl007_pos.py"), "--format", "github"])
        assert rc == 1
        out = capsys.readouterr().out.strip().splitlines()
        annotations = [l for l in out if l.startswith("::error ")]
        assert len(annotations) == 3
        for line in annotations:
            assert "file=" in line and "line=" in line
            assert "title=tracelint TL007" in line
            assert "::`jnp." in line.split(",", 2)[2]  # escaped message body
        assert out[-1].startswith("tracelint: 3 finding(s)")

    def test_github_format_escapes_newlines_and_delimiters(self):
        from dalle_pytorch_tpu.analysis.lint import _gh_escape

        assert _gh_escape("a%b\nc") == "a%25b%0Ac"
        assert _gh_escape("p:q,r", is_property=True) == "p%3Aq%2Cr"

    def test_select_restricts_rules(self):
        from dalle_pytorch_tpu.analysis import main

        assert main([str(FIXTURES / "tl001_pos.py"), "--select", "TL006"]) == 0

    def test_unknown_rule_code_is_usage_error(self):
        from dalle_pytorch_tpu.analysis import main

        assert main(["--select", "TL999"]) == 2

    def test_missing_path_is_usage_error(self, capsys):
        """A typo'd CI path must fail loudly, not lint nothing and pass."""
        from dalle_pytorch_tpu.analysis import main

        assert main(["no/such/dir"]) == 2
        assert "no such file" in capsys.readouterr().err.lower()


def test_package_lint_inprocess_fast_gate():
    """Same gate as the CLI test but in-process (no subprocess import
    cost): the shipped package has zero findings and every suppression
    carries a reason."""
    result = lint_paths([PACKAGE_DIR])
    assert result.clean, "package findings:\n" + "\n".join(
        f.render() for f in result.findings
    )
    assert all(sup.reason for _, sup in result.suppressed)
