"""Pallas flash-decode kernel vs the dense cached-attention oracle.

The acceptance contract of the decode hot-path overhaul: attending a query
chunk against the slot KV cache with per-row live lengths must match dense
attention under the causal-over-prefix mask to fp32 tolerance — across
per-row lengths (continuous-batching slots admitted at different times),
chunk sizes (single-token decode AND multi-token prefill), key padding
(cache lengths that don't divide the K block), and garbage beyond each
row's live prefix (the skip must be a *mask*, not an assumption about
zeroed cache). Runs in Pallas interpret mode on CPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.ops.attention_core import dense_attention
from dalle_pytorch_tpu.ops.pallas_decode import flash_decode_attention


def _oracle(q, k, v, lengths):
    """Dense cached attention: query row i of batch row b attends to cache
    positions <= lengths[b] - n + i — the exact mask models/attention.py
    builds on the dense cached path."""
    n = q.shape[2]
    s = k.shape[2]
    mask = (
        jnp.arange(s)[None, None, :]
        <= (lengths[:, None, None] - n + jnp.arange(n)[None, :, None])
    )
    return dense_attention(q, k, v, mask=mask[:, None])


def _qkv(b, h, n, s, d, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("block_k", [8, 16, 128])
def test_single_token_per_row_lengths(block_k):
    """n=1 decode: every row at its OWN live length, including length 1
    (just-admitted slot) and the full cache."""
    b, h, s, d = 4, 2, 37, 16
    q, k, v = _qkv(b, h, 1, s, d)
    lengths = jnp.asarray([1, 9, 20, s], jnp.int32)
    out = flash_decode_attention(q, k, v, lengths, block_k=block_k)
    np.testing.assert_allclose(
        out, _oracle(q, k, v, lengths), atol=2e-5, rtol=1e-5
    )


@pytest.mark.parametrize("n", [4, 9])
def test_chunk_queries_causal_within_chunk(n):
    """n>1 (prefill / K-token chunk): rows inside the chunk see strictly
    growing prefixes — causality within the chunk must match dense."""
    b, h, s, d = 3, 2, 25, 8
    q, k, v = _qkv(b, h, n, s, d, seed=1)
    lengths = jnp.asarray([n, n + 7, s], jnp.int32)
    out = flash_decode_attention(q, k, v, lengths, block_k=16)
    np.testing.assert_allclose(
        out, _oracle(q, k, v, lengths), atol=2e-5, rtol=1e-5
    )


def test_key_padding_and_garbage_beyond_length():
    """Two hazards at once: the cache length doesn't divide block_k (the
    kernel pads K/V), and positions beyond each row's live length hold
    huge finite garbage (a previous slot occupant's stale keys, scaled up)
    — dead positions must be MASKED, not merely assumed zero: unmasked,
    the 1e4-magnitude logits would dominate every softmax."""
    b, h, s, d = 2, 2, 21, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=2)
    lengths = jnp.asarray([5, 13], jnp.int32)
    poison = jnp.where(
        jnp.arange(s)[None, None, :, None] >= lengths[:, None, None, None],
        1e4,
        0.0,
    )
    out = flash_decode_attention(q, k + poison, v + poison, lengths, block_k=8)
    np.testing.assert_allclose(
        out, _oracle(q, k, v, lengths), atol=2e-5, rtol=1e-5
    )


def test_lockstep_equals_per_row_at_same_length():
    """A batch decoding in lockstep (all lengths equal) must produce the
    same rows as the same data served at per-row lengths that happen to
    coincide — the decode-composition-invariance property at kernel level."""
    b, h, s, d = 3, 2, 32, 16
    q, k, v = _qkv(b, h, 1, s, d, seed=3)
    lock = flash_decode_attention(
        q, k, v, jnp.full((b,), 17, jnp.int32), block_k=8
    )
    per_row = flash_decode_attention(
        q, k, v, jnp.asarray([17, 17, 17], jnp.int32), block_k=8
    )
    np.testing.assert_array_equal(np.asarray(lock), np.asarray(per_row))


def test_under_jit_and_scan():
    """The serving decode loop runs the kernel inside jit(lax.scan(...));
    the traced-lengths path must lower cleanly and stay correct."""
    b, h, s, d = 2, 2, 24, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=4)

    def step(lengths, _):
        out = flash_decode_attention(q, k, v, lengths, block_k=8)
        return lengths + 1, out

    lengths0 = jnp.asarray([3, 11], jnp.int32)
    _, outs = jax.jit(
        lambda l: jax.lax.scan(step, l, None, length=3)
    )(lengths0)
    for i in range(3):
        np.testing.assert_allclose(
            outs[i], _oracle(q, k, v, lengths0 + i), atol=2e-5, rtol=1e-5
        )


def test_bf16_inputs_fp32_accumulation():
    b, h, s, d = 2, 2, 16, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=5)
    lengths = jnp.asarray([7, 16], jnp.int32)
    out = flash_decode_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16), lengths, block_k=8,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(jnp.float32), _oracle(q, k, v, lengths),
        atol=3e-2, rtol=3e-2,
    )


# --------------------------------------------- module-level dispatch wiring


class TestAttentionDispatch:
    """`Attention` cached-path kernel selection (`_use_flash_decode`)."""

    def _run(self, impl, index, seed=0, static_mask=None):
        from dalle_pytorch_tpu.models.attention import Attention

        b, dim, h, dh, s = 2, 32, 2, 8, 21
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(b, 1, dim), jnp.float32)
        cache = {
            "k": jnp.asarray(rng.randn(b, h, s, dh), jnp.float32),
            "v": jnp.asarray(rng.randn(b, h, s, dh), jnp.float32),
            "index": index,
        }
        m = Attention(
            dim=dim, seq_len=s, heads=h, dim_head=dh, attn_impl=impl,
            static_mask=static_mask,
        )
        params = m.init(jax.random.PRNGKey(0), x, cache=cache)
        out, new_cache = m.apply(params, x, cache=cache)
        return out, new_cache

    @pytest.mark.parametrize(
        "index",
        [jnp.int32(7), jnp.asarray([3, 11], jnp.int32)],
        ids=["scalar", "per_row"],
    )
    def test_flash_matches_dense(self, index):
        dense_out, dense_cache = self._run("dense", index)
        flash_out, flash_cache = self._run("flash", index)
        np.testing.assert_allclose(flash_out, dense_out, atol=1e-5, rtol=1e-5)
        np.testing.assert_array_equal(
            np.asarray(dense_cache["index"]), np.asarray(flash_cache["index"])
        )

    def test_pattern_mask_falls_back_to_dense(self):
        """A static pattern mask cannot drive the block skip: flash must
        fall back to the dense row-sliced path, not silently drop the
        mask."""
        s = 21
        sm = np.tril(np.ones((s, s), dtype=bool))
        sm[:, ::2] = False  # an asymmetric pattern the mask must honor
        sm[np.arange(s), np.arange(s)] = True
        dense_out, _ = self._run("dense", jnp.int32(7), static_mask=sm)
        flash_out, _ = self._run("flash", jnp.int32(7), static_mask=sm)
        np.testing.assert_allclose(flash_out, dense_out, atol=1e-5, rtol=1e-5)

    def test_auto_threshold(self, monkeypatch):
        """auto switches on cache length: below the constant the cached
        path stays dense (no pallas lowering), at/above it runs flash."""
        import dalle_pytorch_tpu.models.attention as attention_mod

        calls = []
        real = attention_mod.flash_decode_attention

        def spy(*args, **kwargs):
            calls.append(1)
            return real(*args, **kwargs)

        monkeypatch.setattr(attention_mod, "flash_decode_attention", spy)
        monkeypatch.setattr(attention_mod, "AUTO_FLASH_DECODE_MIN_LEN", 32)
        self._run("auto", jnp.int32(7))  # cache len 21 < 32
        assert not calls
        monkeypatch.setattr(attention_mod, "AUTO_FLASH_DECODE_MIN_LEN", 16)
        self._run("auto", jnp.int32(7))  # 21 >= 16
        assert calls
