"""Fleet trace aggregation: exporter, collector, context propagation.

The acceptance pins (ISSUE 9):

  * two process identities (a bench client + a serving server, distinct
    exporter sites) shipping to one collector yield exactly ONE
    assembled trace whose Perfetto export renders each process as its
    own track, the client span parenting the server spans via the
    propagated x-dalle-trace header — including the out-of-order-arrival
    case (server half ingested first);
  * exporter off => zero serialized spans (counter-gated NULL_EXPORTER,
    the NULL_TRACE idiom);
  * exporter on with the collector unreachable => every request still
    serves, memory stays bounded at `max_buffer`, drops are counted in
    `dalle_obs_export_dropped_total`.

Everything here runs with stubbed transports or localhost HTTP against
fake engines — no model, no device, fast tier.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from dalle_pytorch_tpu.obs import (
    NULL_EXPORTER,
    TRACE_HEADER,
    CollectorServer,
    StructuredLog,
    TraceCollector,
    TraceExporter,
    Tracer,
    format_trace_header,
    parse_trace_header,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

from test_serving_e2e import FakeServingEngine, _get, _post


# ------------------------------------------------------------ header codec


class TestTraceHeaderCodec:
    def test_round_trip(self):
        tid = "deadbeefcafe0123"
        assert parse_trace_header(format_trace_header(tid)) == (tid, None)
        assert parse_trace_header(
            format_trace_header(tid, "site:41:7")
        ) == (tid, "site:41:7")

    def test_exporter_minted_header_round_trips(self):
        tracer = Tracer()
        exp = _StubExporter("http://unused", site="bench-client.02")
        trace = tracer.start_trace("client")
        span = trace.begin("client_request")
        tid, parent = parse_trace_header(exp.context_header(trace, span))
        assert tid == trace.trace_id
        # host is part of the identity: same-site same-pid replicas on
        # two hosts (containers both at pid 1) must not collide
        assert parent == (
            f"bench-client.02:{exp.host}:{exp.pid}:{span.span_id}"
        )

    @pytest.mark.parametrize("bad", [
        None, "", "UPPERHEX0000", "short", "g" * 16,
        "deadbeefcafe0123/bad uid", "deadbeefcafe0123/" + "x" * 200,
        "deadbeefcafe0123/uid/extra",
    ])
    def test_garbage_rejected(self, bad):
        assert parse_trace_header(bad) is None

    def test_trailing_slash_alone_rejected(self):
        assert parse_trace_header("deadbeefcafe0123/") is None


# ---------------------------------------------------------------- exporter


class _StubExporter(TraceExporter):
    """Transport stub: records bodies instead of touching a socket, and
    fails on demand — the backoff/overflow tests drive `_flush_once`
    synchronously (no thread)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.posted = []
        self.fail = False

    def _post(self, body):
        if self.fail:
            raise ConnectionRefusedError("collector down")
        self.posted.append(body)


def _finished_trace(tracer, **args):
    t = tracer.start_trace("request", **args)
    with t.span("queue"):
        pass
    t.finish("ok")
    return t


class TestExporter:
    def test_off_is_null_and_counter_gated(self):
        """No exporter attached: the tracer holds the shared no-op and
        serializes nothing, however much traffic flows."""
        tracer = Tracer()
        assert tracer.exporter is NULL_EXPORTER and not tracer.exporter
        for _ in range(8):
            _finished_trace(tracer)
        assert tracer.exporter.spans_serialized == 0
        assert tracer.exporter.dropped == 0

    def test_finished_traces_ship_as_jsonl(self):
        tracer = Tracer()
        exp = _StubExporter("http://c", site="srv")
        tracer.exporter = exp  # no thread: flush driven synchronously
        t1 = _finished_trace(tracer, rows=1)
        t2 = _finished_trace(tracer, rows=2)
        assert exp.buffered == 2
        assert exp._flush_once() and exp.buffered == 0
        (body,) = exp.posted
        recs = [json.loads(line) for line in body.decode().splitlines()]
        assert [r["trace_id"] for r in recs] == [t1.trace_id, t2.trace_id]
        for rec in recs:
            assert rec["site"] == "srv" and rec["pid"] == exp.pid
            assert rec["outcome"] == "ok" and rec["parent_uid"] is None
            names = {s["name"] for s in rec["spans"]}
            assert names == {"request", "queue"}
            for s in rec["spans"]:
                # wire timestamps are unix seconds, not monotonic
                assert abs(s["t0"] - time.time()) < 60.0
                assert s["t1"] >= s["t0"]
        assert exp.spans_serialized == 4
        assert exp.traces_sent == 2

    def test_backoff_grows_and_resets(self):
        reg = MetricsRegistry()
        exp = _StubExporter(
            "http://c", site="srv", registry=reg,
            backoff_s=0.5, backoff_max_s=4.0,
        )
        tracer = Tracer()
        tracer.exporter = exp
        exp.fail = True
        for i, expect in enumerate((0.5, 1.0, 2.0, 4.0, 4.0)):
            _finished_trace(tracer)
            assert not exp._flush_once()
            assert exp.current_backoff_s == expect
            assert exp.consecutive_failures == i + 1
        assert reg.get("dalle_obs_export_retries_total").value == 5
        # the failed batch went back to the FRONT: nothing was lost yet
        assert exp.buffered == 5
        exp.fail = False
        assert exp._flush_once()
        assert exp.current_backoff_s == 0.0 and exp.consecutive_failures == 0
        assert exp.buffered == 0 and exp.traces_sent == 5

    def test_overflow_drops_oldest_with_counter(self):
        reg = MetricsRegistry()
        exp = _StubExporter("http://c", site="srv", registry=reg,
                            max_buffer=3)
        exp.fail = True
        tracer = Tracer()
        tracer.exporter = exp
        traces = [_finished_trace(tracer, i=i) for i in range(6)]
        assert exp.buffered == 3  # bounded memory, whatever the offered load
        assert exp.dropped == 3
        assert reg.get("dalle_obs_export_dropped_total").value == 3
        exp.fail = False
        assert exp._flush_once()
        recs = [
            json.loads(line) for line in exp.posted[0].decode().splitlines()
        ]
        # the freshest traces survived the overflow
        assert [r["trace_id"] for r in recs] == [
            t.trace_id for t in traces[3:]
        ]

    def test_requeue_after_failure_respects_bound(self):
        exp = _StubExporter("http://c", site="srv", max_buffer=2,
                            max_batch=2)
        exp.fail = True
        tracer = Tracer()
        tracer.exporter = exp
        for i in range(2):
            _finished_trace(tracer, i=i)
        assert not exp._flush_once()  # batch re-queued at the front
        assert exp.buffered == 2
        _finished_trace(tracer, i=99)  # overflow: oldest of the retry drops
        assert exp.buffered == 2 and exp.dropped == 1

    def test_site_sanitized_to_header_alphabet(self):
        """A site with '/', spaces, or ':' would mint parent_uids the
        header codec rejects — silently disabling fleet joins; the
        exporter (and StructuredLog, same clamp) sanitizes instead."""
        tracer = Tracer()
        exp = _StubExporter("http://c", site="eu/replica 0:a")
        trace = tracer.start_trace("client")
        span = trace.begin("hop")
        parsed = parse_trace_header(exp.context_header(trace, span))
        assert parsed is not None and parsed[0] == trace.trace_id
        import io

        buf = io.StringIO()
        StructuredLog(stream=buf, site="eu/replica 0:a").event("x")
        assert json.loads(buf.getvalue())["site"] == exp.site

    def test_stop_final_flush_drains_every_batch(self):
        """stop() ships the WHOLE buffer (in max_batch posts), not one
        batch — a drain-then-shutdown burst must not silently lose the
        tail."""
        exp = _StubExporter("http://c", site="srv", max_batch=2)
        tracer = Tracer()
        # attach minus the thread, so batch boundaries stay deterministic
        tracer.exporter = exp
        exp._tracer = tracer
        for i in range(5):
            _finished_trace(tracer, i=i)
        exp.stop(final_flush=True)
        assert exp.buffered == 0 and exp.traces_sent == 5
        assert len(exp.posted) == 3  # ceil(5/2)
        assert tracer.exporter is NULL_EXPORTER  # detached cleanly

    def test_poisoned_trace_dropped_with_counter_not_fatal(self):
        """A span arg json.dumps cannot serialize (circular ref — even
        default=str can't rescue it) drops THAT trace with a counter;
        the rest of the batch still ships and the shipper survives."""
        exp = _StubExporter("http://c", site="srv")
        tracer = Tracer()
        tracer.exporter = exp
        good1 = _finished_trace(tracer, i=0)
        circular: dict = {}
        circular["self"] = circular
        _finished_trace(tracer, bad=circular)
        good2 = _finished_trace(tracer, i=1)
        assert exp._flush_once()
        assert exp.dropped == 1 and exp.traces_sent == 2
        recs = [
            json.loads(line) for line in exp.posted[0].decode().splitlines()
        ]
        assert [r["trace_id"] for r in recs] == [
            good1.trace_id, good2.trace_id,
        ]

    def test_export_call_is_nonblocking_while_transport_down(self):
        """The serving-path pin at the unit level: export() is a bounded
        append even when every POST fails — no socket on the caller.

        Pinned behaviorally rather than by wall clock (the old
        `100 exports < 1.0s` budget flaked under CI CPU contention):
        `_post` is the exporter's ONLY transport seam, so `export()`
        never running it on the calling thread IS the non-blocking
        property, and bounded-append shows up as buffer + drop
        accounting."""
        exp = _StubExporter("http://c", site="srv", max_buffer=4)
        exp.fail = True
        transport_calls = []
        exp._post = lambda body: transport_calls.append(body)
        tracer = Tracer()
        tracer.exporter = exp
        for _ in range(100):
            _finished_trace(tracer)
        assert transport_calls == [], "export() touched the transport seam"
        assert exp.buffered == 4
        assert exp.dropped == 96  # oldest-out eviction, every drop counted


# --------------------------------------------------------------- collector


def _record(trace_id="deadbeefcafe0123", site="srv", pid=41, host="h1",
            spans=None, parent_uid=None, outcome="ok"):
    return {
        "schema": 1, "trace_id": trace_id, "site": site, "pid": pid,
        "host": host, "outcome": outcome, "parent_uid": parent_uid,
        "spans": spans if spans is not None else [
            {"sid": 0, "parent": None, "name": "request",
             "t0": 100.0, "t1": 100.1, "args": {}},
            {"sid": 1, "parent": 0, "name": "queue",
             "t0": 100.0, "t1": 100.02, "args": {}},
            {"sid": 2, "parent": 0, "name": "chunk",
             "t0": 100.02, "t1": 100.1, "args": {}},
        ],
    }


def _client_record(trace_id="deadbeefcafe0123", pid=7):
    return _record(
        trace_id=trace_id, site="bench", pid=pid, host="h0",
        spans=[
            {"sid": 0, "parent": None, "name": "client",
             "t0": 99.99, "t1": 100.12, "args": {}},
            {"sid": 1, "parent": 0, "name": "client_request",
             "t0": 99.995, "t1": 100.11, "args": {}},
        ],
    )


class TestCollectorJoin:
    def test_two_processes_one_assembled_trace(self):
        col = TraceCollector()
        server_rec = _record(parent_uid="bench:h0:7:1")
        out = col.ingest_lines(
            json.dumps(_client_record()) + "\n" + json.dumps(server_rec)
        )
        assert out == {"accepted": 2, "rejected": 0}
        assert len(col) == 1  # ONE trace, not two
        bundle = col.find("deadbeefcafe0123")
        assert set(bundle.procs) == {"bench@h0:7", "srv@h1:41"}
        assert bundle.procs["srv@h1:41"]["parent_uid"] == "bench:h0:7:1"

    def test_out_of_order_arrival_assembles_identically(self):
        """The server's half landing FIRST (exporters flush on their own
        cadence) must assemble the same one trace with the same parent
        edge."""
        in_order, reversed_order = TraceCollector(), TraceCollector()
        client, server = _client_record(), _record(parent_uid="bench:h0:7:1")
        in_order.ingest_record(client)
        in_order.ingest_record(server)
        reversed_order.ingest_record(server)
        reversed_order.ingest_record(client)
        for col in (in_order, reversed_order):
            assert len(col) == 1
            ev = col.trace_events(trace_id="deadbeefcafe0123")
            tracks = sorted(
                e["args"]["name"] for e in ev["traceEvents"]
                if e["ph"] == "M"
            )
            assert tracks == ["bench (h0:7)", "srv (h1:41)"]
        assert (
            in_order.trace_events("deadbeefcafe0123")
            == reversed_order.trace_events("deadbeefcafe0123")
        )

    def test_duplicate_spans_deduped(self):
        col = TraceCollector()
        rec = _record()
        rec["run"] = "aaaa0001"
        col.ingest_record(rec)
        col.ingest_record(rec)  # an exporter retry re-sends its batch
        bundle = col.find(rec["trace_id"])
        assert len(bundle.spans) == 3
        assert col.duplicate_spans == 3
        ev = col.trace_events(trace_id=rec["trace_id"])
        xs = [e for e in ev["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 3  # never double-rendered

    def test_client_retry_same_header_is_not_a_duplicate(self):
        """A retried request legitimately reuses its x-dalle-trace
        header: same trace_id, same process, FRESH trace instance whose
        span ids restart at 0. The per-instance `run` nonce keeps both
        attempts' spans — only true exporter re-sends dedupe."""
        col = TraceCollector()
        first = _record()
        first["run"] = "aaaa0001"
        retry = _record()
        retry["run"] = "bbbb0002"
        col.ingest_record(first)
        col.ingest_record(retry)
        bundle = col.find(first["trace_id"])
        assert len(bundle.spans) == 6  # both attempts retained
        assert col.duplicate_spans == 0
        ev = col.trace_events(trace_id=first["trace_id"])
        xs = [e for e in ev["traceEvents"] if e["ph"] == "X"]
        assert len([e for e in xs if e["name"] == "chunk"]) == 2

    def test_grace_window_seals_and_late_arrivals_count(self):
        col = TraceCollector(grace_s=10.0)
        col.ingest_record(_client_record(), now=1000.0)
        # inside the window: settling, merges silently
        assert col.sweep(now=1005.0) == 0
        col.ingest_record(_record(parent_uid="bench:h0:7:1"), now=1005.0)
        assert col.late_spans == 0
        bundle = col.find("deadbeefcafe0123")
        assert not bundle.sealed  # still settling inside the window
        # idle past grace_s: sealed
        assert col.sweep(now=1015.1) == 1
        assert bundle.sealed
        # late arrival after sealing: STILL one trace, but counted
        late = _record(site="srv2", pid=42, host="h2", spans=[
            {"sid": 0, "parent": None, "name": "request",
             "t0": 100.0, "t1": 100.05, "args": {}},
        ])
        col.ingest_record(late, now=1016.0)
        assert len(col) == 1
        assert col.late_spans == 1
        assert col.find("deadbeefcafe0123").late_spans == 1

    def test_bounded_retention_evicts_oldest(self):
        col = TraceCollector(max_traces=3)
        for i in range(5):
            col.ingest_record(_record(trace_id=f"{i:016x}"))
        assert len(col) == 3
        assert col.traces_evicted == 2
        assert col.find(f"{0:016x}") is None
        assert col.find(f"{4:016x}") is not None

    def test_malformed_input_counted_never_fatal(self):
        col = TraceCollector()
        out = col.ingest_lines(
            "not json\n"
            + json.dumps({"trace_id": 7}) + "\n"
            + json.dumps(_record(spans=[
                {"sid": "bad", "name": "x", "t0": 1, "t1": 2},
                {"sid": 1, "parent": None, "name": "ok",
                 "t0": 1.0, "t1": 2.0},
            ]))
        )
        assert out["rejected"] == 2 and out["accepted"] == 1
        assert col.bad_records == 2 and col.bad_spans == 1
        assert len(col.find(_record()["trace_id"]).spans) == 1

    def test_flow_events_bind_client_span_to_server_root(self):
        col = TraceCollector()
        col.ingest_record(_client_record())
        col.ingest_record(_record(parent_uid="bench:h0:7:1"))
        ev = col.trace_events(trace_id="deadbeefcafe0123")["traceEvents"]
        pids = {
            e["args"]["name"]: e["pid"] for e in ev if e["ph"] == "M"
        }
        flows = {e["ph"]: e for e in ev if e["ph"] in ("s", "f")}
        assert set(flows) == {"s", "f"}
        # arrow starts on the client's track, finishes on the server's
        assert flows["s"]["pid"] == pids["bench (h0:7)"]
        assert flows["f"]["pid"] == pids["srv (h1:41)"]
        # the server root's uid is addressable in args (join debugging)
        server_req = [
            e for e in ev
            if e["ph"] == "X" and e["args"].get("uid") == "srv:h1:41:0"
        ]
        assert len(server_req) == 1 and server_req[0]["name"] == "request"


class TestCriticalPath:
    def test_stage_percentiles_and_dominant_attribution(self):
        col = TraceCollector()
        # 3 traces: chunk dominates two, queue dominates one
        for i, (queue_s, chunk_s) in enumerate(
            [(0.01, 0.08), (0.01, 0.06), (0.2, 0.05)]
        ):
            t0 = 100.0
            col.ingest_record(_record(
                trace_id=f"{i:016x}",
                spans=[
                    {"sid": 0, "parent": None, "name": "request",
                     "t0": t0, "t1": t0 + queue_s + chunk_s, "args": {}},
                    {"sid": 1, "parent": 0, "name": "queue",
                     "t0": t0, "t1": t0 + queue_s, "args": {}},
                    {"sid": 2, "parent": 0, "name": "chunk",
                     "t0": t0 + queue_s, "t1": t0 + queue_s + chunk_s,
                     "args": {}},
                ],
            ))
        cp = col.critical_path()
        assert cp["traces"] == 3
        assert cp["stages"]["chunk"]["count"] == 3
        assert cp["stages"]["chunk"]["p50_ms"] == 60.0
        assert cp["stages"]["queue"]["p95_ms"] == 200.0
        dom = cp["critical_path"]["dominant"]
        assert dom["chunk"]["traces"] == 2
        assert dom["queue"] == {"traces": 1, "fraction": 0.333}
        attr = cp["critical_path"]["attributed_ms"]
        assert attr["chunk"]["count"] == 3

    def test_parent_covering_spans_excluded_from_attribution(self):
        """The per-process root (and the client's enclosing span) cover
        their children and must not double-count."""
        col = TraceCollector()
        col.ingest_record(_client_record())
        col.ingest_record(_record(parent_uid="bench:h0:7:1"))
        cp = col.critical_path()
        assert "request" not in cp["stages"]
        assert "client_request" not in cp["stages"]
        assert {"queue", "chunk"} <= set(cp["stages"])

    def test_untraced_gap_attributed(self):
        col = TraceCollector()
        col.ingest_record(_record(spans=[
            {"sid": 0, "parent": None, "name": "request",
             "t0": 100.0, "t1": 100.2, "args": {}},
            {"sid": 1, "parent": 0, "name": "queue",
             "t0": 100.0, "t1": 100.05, "args": {}},
            # 0.1s of host time no span claims
            {"sid": 2, "parent": 0, "name": "chunk",
             "t0": 100.15, "t1": 100.2, "args": {}},
        ]))
        attr = col.critical_path()["critical_path"]["attributed_ms"]
        assert attr["(untraced)"]["p50_ms"] == 100.0


# ----------------------------------------------------- collector over HTTP


@pytest.fixture()
def collector_server():
    server = CollectorServer(grace_s=0.05).start()
    try:
        yield server
    finally:
        server.shutdown()


def _collector_get(server, path):
    with urllib.request.urlopen(
        f"{server.url}{path}", timeout=10
    ) as resp:
        return resp.status, json.loads(resp.read())


class TestCollectorHTTP:
    def test_ingest_traces_critical_path_healthz(self, collector_server):
        body = (
            json.dumps(_client_record()) + "\n"
            + json.dumps(_record(parent_uid="bench:h0:7:1")) + "\n"
        ).encode()
        req = urllib.request.Request(
            f"{collector_server.url}/ingest", data=body, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read()) == {
                "accepted": 2, "rejected": 0,
            }
        status, payload = _collector_get(collector_server, "/traces")
        assert status == 200
        assert len(
            [e for e in payload["traceEvents"] if e["ph"] == "M"]
        ) == 2
        status, payload = _collector_get(
            collector_server, "/traces?trace_id=deadbeefcafe0123"
        )
        assert status == 200 and payload["traceEvents"]
        status, payload = _collector_get(collector_server, "/critical_path")
        assert status == 200 and payload["traces"] == 1
        status, payload = _collector_get(collector_server, "/healthz")
        assert status == 200 and payload["status"] == "ok"
        assert payload["records_ingested"] == 2

    def test_unknown_trace_404_and_bad_n_400(self, collector_server):
        for path, code in (
            ("/traces?trace_id=ffffffffffffffff", 404),
            ("/traces?n=0", 400),
            ("/nope", 404),
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _collector_get(collector_server, path)
            assert e.value.code == code


# ------------------------------------- acceptance: serving e2e over HTTP


class TestFleetE2E:
    """A bench client + one serving server (distinct exporter sites, one
    process) exporting to one collector: the ISSUE's acceptance pin."""

    def _serve_one(self, collector_url, site, header=None, out_of_order=False):
        exporter = TraceExporter(collector_url, site=site)
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            tracer=Tracer(max_traces=16), exporter=exporter,
        ).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/generate",
                data=json.dumps({"prompt": "fleet"}).encode(),
                headers={"Content-Type": "application/json",
                         **({TRACE_HEADER: header} if header else {})},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            assert exporter.flush(timeout_s=10.0)
            return payload
        finally:
            server.shutdown()

    def test_client_and_server_stitch_into_one_trace(self):
        collector = CollectorServer(grace_s=0.05).start()
        client_exp = TraceExporter(collector.url, site="bench")
        client_tracer = Tracer()
        client_exp.attach(client_tracer)
        try:
            trace = client_tracer.start_trace("client")
            span = trace.begin("client_request")
            try:
                header = client_exp.context_header(trace, span)
                payload = self._serve_one(
                    collector.url, "srv", header=header
                )
            finally:
                trace.end(span)
            trace.finish("ok")
            assert client_exp.flush(timeout_s=10.0)

            # the server ADOPTED the propagated trace id
            assert payload["trace_id"] == trace.trace_id
            col = collector.collector
            assert len(col) == 1  # exactly ONE assembled trace
            bundle = col.find(trace.trace_id)
            assert len(bundle.procs) == 2
            srv_proc = next(
                p for p in bundle.procs.values() if p["site"] == "srv"
            )
            assert srv_proc["parent_uid"] == client_exp.span_uid(span)

            ev = col.trace_events(trace_id=trace.trace_id)["traceEvents"]
            tracks = [e["args"]["name"] for e in ev if e["ph"] == "M"]
            assert len(tracks) == 2  # one track per process identity
            assert any(t.startswith("bench ") for t in tracks)
            assert any(t.startswith("srv ") for t in tracks)
            names = {e["name"] for e in ev if e["ph"] == "X"}
            # client stage + the server's full stage vocabulary, merged
            assert {"client_request", "request", "queue", "generate",
                    "respond"} <= names
            assert {e["ph"] for e in ev} >= {"s", "f"}  # the parent arrow
            # the export is valid JSON end to end
            json.loads(json.dumps(col.trace_events()))
        finally:
            collector.shutdown()
            client_exp.stop(final_flush=False)

    def test_absent_header_mints_locally(self):
        collector = CollectorServer(grace_s=0.05).start()
        try:
            payload = self._serve_one(collector.url, "solo", header=None)
            bundle = collector.collector.find(payload["trace_id"])
            assert bundle is not None
            (proc,) = bundle.procs.values()
            assert proc["site"] == "solo" and proc["parent_uid"] is None
        finally:
            collector.shutdown()

    def test_malformed_header_rejected_not_adopted(self):
        collector = CollectorServer(grace_s=0.05).start()
        try:
            payload = self._serve_one(
                collector.url, "srv", header="NOT-A-TRACE/###",
            )
            # a fresh 16-hex id was minted instead of adopting garbage
            assert parse_trace_header(payload["trace_id"]) == (
                payload["trace_id"], None,
            )
        finally:
            collector.shutdown()

    def test_collector_down_serving_unaffected(self):
        """The other acceptance pin: exporter on, collector unreachable
        — every request serves, buffer memory bounded, drops counted.

        Deflaked (the PR 9 contention flake): the shipper thread is OFF
        (`thread=False`) so the buffer-overflow drops happen
        DETERMINISTICALLY on the request threads' own export() calls —
        8 exports into a 4-trace buffer are exactly 4 drops — and the
        transport failure is driven synchronously with one
        `_flush_once()` instead of a wall-clock wait on thread
        scheduling. The serving-path property under test (export never
        blocks or errors a request) is identical either way."""
        import socket

        # grab a port that is certainly closed
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        reg = MetricsRegistry()
        exporter = TraceExporter(
            f"http://127.0.0.1:{dead_port}", site="srv", registry=reg,
            max_buffer=4, backoff_s=0.05, timeout_s=0.5, thread=False,
        )
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            tracer=Tracer(max_traces=32), exporter=exporter,
        ).start()
        try:
            for i in range(8):
                status, payload = _post(
                    server.port, {"prompt": f"req {i}"}
                )
                assert status == 200 and payload["trace_id"]
            assert exporter.buffered == exporter.max_buffer
            assert exporter.dropped == 4
            assert reg.get("dalle_obs_export_dropped_total").value == 4
            # one synchronous ship attempt: the dead port fails the
            # POST, the batch re-queues at the front, backoff engages
            assert exporter._flush_once() is False
            assert exporter.buffered == exporter.max_buffer
            assert exporter.consecutive_failures == 1
            assert exporter.current_backoff_s > 0
            # the postmortem dump names the export failure
            dump = server.state_dump()
            assert dump["trace_export"]["last_error"]
        finally:
            server.shutdown()  # final flush is best-effort and bounded


# ----------------------------------------------------- log identity fields


class TestLogIdentity:
    def test_every_line_carries_site_pid_host(self):
        import io
        import os

        buf = io.StringIO()
        log = StructuredLog(stream=buf, site="replica-3")
        log.event("stall", reason="dispatch_stuck")
        log.request(trace_id="t1", outcome="ok", status=200, latency_ms=1.0)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert len(lines) == 2
        for rec in lines:
            assert rec["site"] == "replica-3"
            assert rec["pid"] == os.getpid()
            assert rec["host"]

    def test_site_defaults_stable(self):
        import io

        buf = io.StringIO()
        log = StructuredLog(stream=buf)
        log.event("a")
        log.event("b")
        recs = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert recs[0]["site"] == recs[1]["site"] != ""
