"""Decode-state migration: checkpoint codec, mid-decode resume, drain
with migration, crash-spool recovery.

The load-bearing contracts:

  * A MIGRATED REQUEST IS THE SAME REQUEST — tokens bit-identical to an
    unmigrated run, pinned for the slotted and paged engines, including
    resumes that land mid-flight next to live traffic. The resume path
    restores completed rows verbatim and continues partial rows from
    their checkpointed position via one teacher-forced re-prefill
    (`models/dalle.py:decode_resume`), so it re-decodes strictly fewer
    tokens than a from-scratch failover.
  * A BAD CHECKPOINT IS A CLEAN RESTART, NEVER AN ERROR — fingerprint
    mismatch (different build), corrupt/truncated payload, or a
    checkpoint inconsistent with its request all degrade to a counted
    position-0 restart; the client sees a normal 200.
  * DRAIN?MIGRATE=1 IS A ZERO-LOST-WORK DRAIN — the replica exports
    every queued + in-flight request at the next chunk boundary (409 +
    checkpoint per request), and the fleet router re-dispatches each as
    a resume with full attribution.
  * THE CRASH SPOOL SURVIVES A SIGKILL — the beacon journal is atomic
    and bounded; the supervisor hands it to the router, whose failover
    path resumes from it.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.obs.tracing import Tracer
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.faults import FaultInjector
from dalle_pytorch_tpu.serving.migrate import (
    CheckpointCorrupt,
    CheckpointMismatch,
    CheckpointSpool,
    MigratedError,
    RequestCheckpoint,
    RowCheckpoint,
    decode_checkpoint,
    encode_checkpoint,
    from_wire,
    to_wire,
)
from dalle_pytorch_tpu.serving.router import (
    CheckpointRegistry,
    FleetRouter,
    RouterServer,
    parse_request_key,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


@pytest.fixture(scope="module")
def toy():
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


def _engine(toy, paged=False, resume=True, max_batch=2, **kw):
    model, params = toy
    cls = PagedContinuousEngine if paged else ContinuousEngine
    if paged:
        kw.setdefault("page_size", 4)
    eng = cls(
        model=model, variables=params, max_batch=max_batch,
        chunk_tokens=2, prefill_batch=max_batch,
        registry=MetricsRegistry(), resume_enabled=resume, **kw,
    )
    eng.tokenizer = ByteTokenizer()
    return eng


def _cp(rows=None, **kw):
    if rows is None:
        rows = [RowCheckpoint(
            row_index=0,
            prompt_ids=np.arange(TEXT_SEQ, dtype=np.int32),
            tokens=np.asarray([3, 1, 4], np.int32),
            done=False, seed=7, temperature=0.9, top_k=0.8,
        )]
    kw.setdefault("chunk_index", 5)
    kw.setdefault("priority", "normal")
    kw.setdefault("site", "replica-a")
    kw.setdefault("request_key", "abc123")
    return RequestCheckpoint(rows=rows, **kw)


# ------------------------------------------------------------------ codec


class TestCodec:
    def test_round_trip(self):
        cp = _cp(rows=[
            RowCheckpoint(0, np.arange(TEXT_SEQ, dtype=np.int32),
                          np.arange(IMG_SEQ, dtype=np.int32), True, 11,
                          0.7, 0.95),
            RowCheckpoint(1, np.arange(TEXT_SEQ, dtype=np.int32),
                          np.asarray([5, 9], np.int32), False, 12),
        ], tenant="t1", trace_id="deadbeefdeadbeef")
        blob = encode_checkpoint(cp, "fp-1")
        back = decode_checkpoint(blob, "fp-1")
        assert len(back.rows) == 2
        assert back.rows[0].done and back.rows[0].pos == IMG_SEQ
        assert back.rows[1].pos == 2 and not back.rows[1].done
        np.testing.assert_array_equal(
            back.rows[0].tokens, np.arange(IMG_SEQ)
        )
        np.testing.assert_array_equal(
            back.rows[1].prompt_ids, np.arange(TEXT_SEQ)
        )
        assert (back.rows[1].seed, back.rows[0].temperature) == (12, 0.7)
        assert back.chunk_index == 5 and back.site == "replica-a"
        assert back.tenant == "t1" and back.request_key == "abc123"
        assert back.done_tokens() == IMG_SEQ  # partial rows don't count
        # wire transport round-trips the exact bytes
        assert from_wire(to_wire(blob)) == blob

    def test_fingerprint_mismatch_raises_mismatch(self):
        blob = encode_checkpoint(_cp(), "fp-build-1")
        with pytest.raises(CheckpointMismatch):
            decode_checkpoint(blob, "fp-build-2")

    def test_truncated_and_garbled_raise_corrupt(self):
        blob = encode_checkpoint(_cp(), "fp")
        with pytest.raises(CheckpointCorrupt):
            decode_checkpoint(blob[:-3], "fp")  # truncated payload
        garbled = bytearray(blob)
        garbled[-5] ^= 0xFF
        with pytest.raises(CheckpointCorrupt):
            decode_checkpoint(bytes(garbled), "fp")  # checksum
        with pytest.raises(CheckpointCorrupt):
            decode_checkpoint(b"NOTMAGIC" + blob, "fp")
        with pytest.raises(CheckpointCorrupt):
            from_wire("!!! not base64 !!!")

    def test_format_drift_is_mismatch_not_corrupt(self):
        import dalle_pytorch_tpu.serving.migrate as mig

        blob = encode_checkpoint(_cp(), "fp")
        # rewrite the header with a bumped format version, keeping the
        # checksum valid — an OLD reader of a NEW checkpoint must see a
        # clean mismatch (counted cold restart), not a parse error
        rest = blob[len(mig.CKPT_MAGIC):]
        nl = rest.index(b"\n")
        header = json.loads(rest[:nl])
        header["format"] = mig.CKPT_FORMAT + 1
        blob2 = (
            mig.CKPT_MAGIC
            + json.dumps(header, sort_keys=True,
                         separators=(",", ":")).encode()
            + b"\n" + rest[nl + 1:]
        )
        with pytest.raises(CheckpointMismatch):
            decode_checkpoint(blob2, "fp")


# ------------------------------------------------------------------ spool


class TestSpool:
    def test_write_read_clear(self, tmp_path):
        spool = CheckpointSpool(tmp_path)
        blob = encode_checkpoint(_cp(), "fp")
        spool.write({"k1": blob, "k2": blob})
        assert spool.read() == {"k1": blob, "k2": blob}
        # latest-state-only: a new write REPLACES the journal
        spool.write({"k3": blob})
        assert set(spool.read()) == {"k3"}
        spool.clear()
        assert spool.read() == {}

    def test_corrupt_entry_skipped_via_fault_seam(self, tmp_path):
        spool = CheckpointSpool(tmp_path)
        blob = encode_checkpoint(_cp(), "fp")
        spool.write({"k1": blob})
        spool.faults = FaultInjector().corrupt_cache("spool", mode="truncate")
        out = spool.read()  # truncated tail line is skipped, not fatal
        assert out == {} or all(v == blob for v in out.values())
        assert spool.faults.fired

    def test_byte_cap_drops_largest_first(self, tmp_path):
        small = encode_checkpoint(_cp(), "fp")
        big = encode_checkpoint(_cp(rows=[
            RowCheckpoint(0, np.arange(TEXT_SEQ, dtype=np.int32),
                          np.zeros(IMG_SEQ, np.int32), True, 1)
            for _ in range(64)
        ]), "fp")
        cap = int(len(to_wire(small)) * 3)
        spool = CheckpointSpool(tmp_path, max_bytes=cap + 256)
        spool.write({"small": small, "big": big})
        kept = spool.read()
        assert "small" in kept and "big" not in kept
        assert spool.dropped_entries == 1


# -------------------------------------------------- batcher-level export


def _submit(batcher, specs, **kw):
    return batcher.submit(specs, timeout_s=60, **kw)


def _specs(n=1, seed=100, text=None):
    if text is None:
        text = np.arange(TEXT_SEQ, dtype=np.int32) % 5 + 1
    return [
        SampleSpec(text_ids=text, seed=seed + i) for i in range(n)
    ]


def _hold_mid_decode(eng, nth=3, seconds=2.0):
    """Deterministically park the worker INSIDE chunk dispatch `nth`
    (a few chunks of real progress first), so the test can request an
    export that is guaranteed to find the request mid-decode at the
    next boundary."""
    eng.faults = FaultInjector().stall_nth("chunk", nth, seconds=seconds)


def _wait_fired(eng, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not eng.faults.fired and time.monotonic() < deadline:
        time.sleep(0.005)
    assert eng.faults.fired, "stall rule never fired"


class TestMigrateOut:
    def test_drain_exports_inflight_and_queued(self, toy):
        eng = _engine(toy, max_batch=2)
        batcher = ContinuousBatcher(eng, registry=eng.registry)
        try:
            # two in-flight rows + one queued request (no free slots);
            # the stall pins the request mid-decode while we drain
            _hold_mid_decode(eng)
            r1 = _submit(batcher, _specs(2, seed=200))
            _wait_fired(eng)
            assert batcher.inflight_rows == 2
            r2 = _submit(batcher, _specs(1, seed=300))
            cps = batcher.migrate_out(timeout_s=30)
            assert cps is not None and len(cps) == 2
            for req in (r1, r2):
                with pytest.raises(MigratedError) as e:
                    req.future.result(timeout=10)
                cp = e.value.checkpoint
                assert all(not row.done for row in cp.rows)
            # the in-flight request's rows carry real decode progress
            cp1 = next(
                e for e in cps if len(e.rows) == 2
            )
            assert any(row.pos > 0 for row in cp1.rows)
            # slots freed; the batcher serves new work afterwards
            assert batcher.inflight_rows == 0
            r3 = _submit(batcher, _specs(1, seed=400))
            toks, _ = r3.future.result(timeout=60)
            assert toks.shape == (1, IMG_SEQ)
        finally:
            batcher.shutdown(drain=False)

    def test_idle_migrate_returns_empty(self, toy):
        eng = _engine(toy, max_batch=2)
        batcher = ContinuousBatcher(eng, registry=eng.registry)
        try:
            assert batcher.migrate_out(timeout_s=10) == []
        finally:
            batcher.shutdown(drain=False)

    def test_peek_checkpoints_nondestructive(self, toy):
        eng = _engine(toy, max_batch=2)
        batcher = ContinuousBatcher(eng, registry=eng.registry)
        try:
            _hold_mid_decode(eng)
            req = _submit(batcher, _specs(1, seed=500))
            _wait_fired(eng)
            cps = batcher.peek_checkpoints(timeout_s=30)
            assert cps is not None and len(cps) == 1
            # the request keeps decoding here and completes normally
            toks, _ = req.future.result(timeout=60)
            assert toks.shape == (1, IMG_SEQ)
        finally:
            batcher.shutdown(drain=False)


# ------------------------------------------- resume bit-identity (engines)


def _reference(toy, paged, specs):
    eng = _engine(toy, paged=paged, resume=False, max_batch=len(specs))
    batcher = ContinuousBatcher(eng, registry=eng.registry)
    try:
        req = _submit(batcher, specs)
        toks, _ = req.future.result(timeout=120)
        return np.asarray(toks)
    finally:
        batcher.shutdown(drain=False)


def _clone_specs(specs):
    return [
        SampleSpec(text_ids=s.text_ids, seed=s.seed,
                   temperature=s.temperature, top_k=s.top_k)
        for s in specs
    ]


class TestResumeBitIdentity:
    @pytest.mark.parametrize("paged", [False, True])
    def test_migrated_resume_bit_identical(self, toy, paged):
        """Export mid-decode from one batcher, resume on a FRESH engine
        via submit(resume=...) — final tokens equal the unmigrated run,
        and the resumed engine re-decodes strictly fewer tokens."""
        specs = [
            SampleSpec(np.arange(TEXT_SEQ, dtype=np.int32) % 5 + 1,
                       seed=41, temperature=0.8),
            SampleSpec((np.arange(TEXT_SEQ, dtype=np.int32) * 3) % 7 + 1,
                       seed=42),
        ]
        ref = _reference(toy, paged, specs)

        eng_a = _engine(toy, paged=paged, max_batch=2)
        ba = ContinuousBatcher(eng_a, registry=eng_a.registry)
        try:
            _hold_mid_decode(eng_a)
            req = _submit(ba, _clone_specs(specs))
            _wait_fired(eng_a)
            cps = ba.migrate_out(timeout_s=30)
            assert cps and len(cps) == 1
            with pytest.raises(MigratedError):
                req.future.result(timeout=10)
            cp = cps[0]
            assert any(0 < r.pos < IMG_SEQ for r in cp.rows), (
                "drain did not catch the request mid-decode"
            )
        finally:
            ba.shutdown(drain=False)

        # wire round-trip through the codec, like the router would
        fp = eng_a.resume_fingerprint()
        cp2 = decode_checkpoint(
            from_wire(to_wire(encode_checkpoint(cp, fp))), fp
        )

        eng_b = _engine(toy, paged=paged, max_batch=2)
        assert eng_b.resume_fingerprint() == fp
        bb = ContinuousBatcher(eng_b, registry=eng_b.registry)
        try:
            req2 = bb.submit(
                _clone_specs(specs), timeout_s=120, resume=cp2,
                resume_bytes=128,
            )
            toks, _ = req2.future.result(timeout=120)
            np.testing.assert_array_equal(np.asarray(toks), ref)
            decoded = int(
                eng_b.registry.get(
                    "dalle_serving_decoded_tokens_total"
                ).value
            )
            restored = sum(r.pos for r in cp2.rows)
            assert decoded <= 2 * IMG_SEQ - restored, (
                f"resume re-decoded {decoded} tokens; expected at most "
                f"{2 * IMG_SEQ - restored} (restored {restored})"
            )
            resumed = int(
                eng_b.registry.get(
                    "dalle_serving_resumed_tokens_total"
                ).value
            )
            assert resumed == restored
        finally:
            bb.shutdown(drain=False)

    def test_resume_mid_flight_next_to_live_traffic(self, toy):
        """A resume admitted while another request is decoding: both
        complete bit-identically (the composition-invariance contract
        extends to the resume program)."""
        specs_a = [SampleSpec(
            np.arange(TEXT_SEQ, dtype=np.int32) % 6 + 1, seed=61,
        )]
        specs_b = [SampleSpec(
            (np.arange(TEXT_SEQ, dtype=np.int32) * 2) % 6 + 1, seed=62,
        )]
        ref_a = _reference(toy, False, specs_a)
        ref_b = _reference(toy, False, specs_b)

        # build a checkpoint for A at position 4 from the reference
        cp = RequestCheckpoint(rows=[RowCheckpoint(
            0, specs_a[0].text_ids, np.asarray(ref_a[0][:4], np.int32),
            False, 61,
        )], chunk_index=2, site="elsewhere")

        eng = _engine(toy, max_batch=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            live = _submit(b, _clone_specs(specs_b))
            deadline = time.monotonic() + 30
            while b.inflight_rows < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            resumed = b.submit(
                _clone_specs(specs_a), timeout_s=120, resume=cp,
            )
            toks_a, _ = resumed.future.result(timeout=120)
            toks_b, _ = live.future.result(timeout=120)
            np.testing.assert_array_equal(np.asarray(toks_a), ref_a)
            np.testing.assert_array_equal(np.asarray(toks_b), ref_b)
        finally:
            b.shutdown(drain=False)

    def test_fully_done_checkpoint_completes_without_decode(self, toy):
        ref = _reference(toy, False, _specs(1, seed=77))
        cp = RequestCheckpoint(rows=[RowCheckpoint(
            0, _specs(1, seed=77)[0].text_ids,
            np.asarray(ref[0], np.int32), True, 77,
        )], site="elsewhere")
        eng = _engine(toy, max_batch=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            req = b.submit(_specs(1, seed=77), timeout_s=60, resume=cp)
            toks, _ = req.future.result(timeout=60)
            np.testing.assert_array_equal(np.asarray(toks), ref)
            assert int(eng.registry.get(
                "dalle_serving_decoded_tokens_total"
            ).value) == 0
        finally:
            b.shutdown(drain=False)

    def test_preemption_uses_resume_path_when_supported(self, toy):
        """On a resume-capable engine a preempted low request re-enters
        at its preempted position — resumed tokens counted, output
        bit-identical to the undisturbed run."""
        ref = _reference(toy, False, _specs(2, seed=88))
        eng = _engine(toy, max_batch=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            # hold the low request mid-decode so the high arrival
            # deterministically finds it occupying both slots
            _hold_mid_decode(eng, nth=2, seconds=1.0)
            low = b.submit(
                _specs(2, seed=88), timeout_s=120, priority="low",
            )
            _wait_fired(eng)
            high = b.submit(
                _specs(1, seed=99), timeout_s=120, priority="high",
            )
            toks_h, _ = high.future.result(timeout=120)
            toks_l, _ = low.future.result(timeout=120)
            np.testing.assert_array_equal(np.asarray(toks_l), ref)
            assert low.preemptions >= 1
        finally:
            b.shutdown(drain=False)


# -------------------------------------------------------- HTTP + routers


def _http(method, port, path, body=None, timeout=60, headers=None):
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


def _server(toy, paged=False, **kw):
    eng = _engine(toy, paged=paged, max_batch=2)
    return eng, ServingServer(
        eng, port=0, request_timeout_s=60,
        tracer=Tracer(max_traces=32), **kw,
    ).start()


class TestHTTPMigration:
    def test_drain_migrate_409_and_resume_on_second_replica(self, toy):
        eng_a, srv_a = _server(toy)
        eng_b, srv_b = _server(toy)
        try:
            body = {"prompt": "red circle", "seed": 321, "num_images": 2,
                    "timeout_s": 60}
            status, ref = _http("POST", srv_b.port, "/generate", body)
            assert status == 200

            out = {}

            def client():
                try:
                    out["resp"] = _http(
                        "POST", srv_a.port, "/generate", body,
                    )
                except urllib.error.HTTPError as exc:
                    out["code"] = exc.code
                    out["body"] = json.loads(exc.read() or b"{}")

            _hold_mid_decode(eng_a)
            t = threading.Thread(target=client)
            t.start()
            _wait_fired(eng_a)
            status, drain = _http(
                "POST", srv_a.port, "/admin/drain?migrate=1", body={},
            )
            assert status == 200
            assert drain["migrate"]["supported"] is True
            assert drain["migrate"]["migrated"] == 1
            assert drain["quiesced"] is True
            t.join(timeout=30)
            # the in-flight client got the 409 + checkpoint
            assert out.get("code") == 409
            assert out["body"]["migrated"] is True
            wire = out["body"]["checkpoint"]

            # resume on replica B: bit-identical to B's own reference
            status, payload = _http(
                "POST", srv_b.port, "/generate",
                {**body, "resume": wire},
            )
            assert status == 200
            assert payload["tokens"] == ref["tokens"]
        finally:
            srv_a.shutdown()
            srv_b.shutdown()

    @pytest.mark.parametrize(
        "mangle, reason",
        [
            (lambda w: to_wire(b"NOTMAGIC" + from_wire(w)), "corrupt"),
            (lambda w: w, "mismatch"),  # re-encoded under a fake fp below
            (lambda w: w, "inconsistent"),  # body mutated below
        ],
    )
    def test_bad_resume_degrades_to_clean_restart(self, toy, mangle,
                                                  reason):
        eng, srv = _server(toy)
        try:
            body = {"prompt": "blue square", "seed": 555, "timeout_s": 60}
            status, ref = _http("POST", srv.port, "/generate", body)
            assert status == 200

            # a plausible checkpoint for this request
            text_ids = eng.tokenize("blue square")
            cp = RequestCheckpoint(rows=[RowCheckpoint(
                0, text_ids, np.asarray(ref["tokens"][0][:3], np.int32),
                False, 555,
            )], site="x")
            if reason == "mismatch":
                wire = to_wire(encode_checkpoint(cp, "some-other-build"))
                req_body = {**body, "resume": wire}
            elif reason == "inconsistent":
                wire = to_wire(
                    encode_checkpoint(cp, srv.resume_fingerprint)
                )
                # same checkpoint, different seed -> must NOT resume
                req_body = {**body, "seed": 556, "resume": wire}
            else:
                wire = mangle(to_wire(
                    encode_checkpoint(cp, srv.resume_fingerprint)
                ))
                req_body = {**body, "resume": wire}
            status, payload = _http(
                "POST", srv.port, "/generate", req_body,
            )
            assert status == 200  # never a client-visible error
            if reason != "inconsistent":  # same seed: same tokens
                assert payload["tokens"] == ref["tokens"]
            fam = srv.registry.get("dalle_serving_resume_rejects_total")
            counts = {label: int(c.value) for label, c in fam.items()}
            assert counts.get(reason) == 1, counts
        finally:
            srv.shutdown()

    def test_admin_checkpoints_pull(self, toy):
        eng, srv = _server(toy)
        try:
            body = {"prompt": "pull", "seed": 777, "timeout_s": 60}
            _hold_mid_decode(eng)
            t = threading.Thread(
                target=lambda: _http("POST", srv.port, "/generate", body),
            )
            t.start()
            _wait_fired(eng)
            status, out = _http("GET", srv.port, "/admin/checkpoints")
            assert status == 200
            assert out["count"] == 1
            (wire,) = out["checkpoints"].values()
            cp = decode_checkpoint(
                from_wire(wire), srv.resume_fingerprint
            )
            assert len(cp.rows) == 1
            t.join(timeout=60)
        finally:
            srv.shutdown()


class TestRouterMigration:
    def _fleet(self, toy, **router_kw):
        engs, servers = [], []
        for _ in range(2):
            e, s = _server(toy)
            engs.append(e)
            servers.append(s)
        router = FleetRouter(
            [f"r{i}=http://127.0.0.1:{s.port}"
             for i, s in enumerate(servers)],
            registry=MetricsRegistry(), **router_kw,
        )
        front = RouterServer(router, port=0, probes=False).start()
        return engs, servers, router, front

    def test_drain_migrate_redispatches_bit_identical(self, toy):
        engs, servers, router, front = self._fleet(toy)
        try:
            port = front.port
            body = {"prompt": "drain me", "seed": 901, "num_images": 2,
                    "timeout_s": 60}
            status, ref = _http("POST", port, "/generate", body)
            assert status == 200

            results = []

            def client():
                results.append(_http("POST", port, "/generate", body))

            # park the request mid-decode on whichever replica gets it
            # (stalls armed AFTER the reference pass), then drain the
            # holder with migrate — the router must re-dispatch the 409
            # as a resume and answer 200
            for e in engs:
                _hold_mid_decode(e, seconds=4.0)
            t = threading.Thread(target=client)
            t.start()
            deadline = time.monotonic() + 30
            while not any(e.faults.fired for e in engs) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            holder = 0 if engs[0].faults.fired else 1
            # disarm the other replica's stall so the resume runs clean
            engs[1 - holder].faults = None
            detail = router.drain(f"r{holder}", wait_s=30.0, migrate=True)
            assert detail["mode"] == "drained"
            t.join(timeout=60)
            assert results and results[0][0] == 200
            assert results[0][1]["tokens"] == ref["tokens"]
            migs = {
                label: int(c.value)
                for label, c in router.registry.get(
                    "dalle_router_migrations_total"
                ).items()
            }
            assert migs.get("drain", 0) >= 1
            # the resuming replica restored tokens instead of re-decoding
            other = 1 - holder
            resumed = int(engs[other].registry.get(
                "dalle_serving_resumed_tokens_total"
            ).value)
            assert resumed > 0
            # attribution: /debug/replicas carries the migration block
            assert router.detail()["migration"]["migrations"].get(
                "drain", 0
            ) >= 1
        finally:
            front.shutdown()
            for s in servers:
                s.shutdown()

    def test_spool_ingest_feeds_crash_failover(self, toy):
        """Transport-failed request + spooled checkpoint => the
        re-dispatch resumes (reason=crash) and completes bit-identically."""
        engs, servers, router, front = self._fleet(
            toy, migrate_wait_s=5.0,
        )
        try:
            port = front.port
            body = {"prompt": "crash me", "seed": 911, "num_images": 2,
                    "timeout_s": 60}
            status, ref = _http("POST", port, "/generate", body)
            assert status == 200

            from dalle_pytorch_tpu.serving.router import (
                request_fingerprint,
            )

            qkey = request_fingerprint(dict(body))
            # build the checkpoint the dead replica would have spooled
            text_ids = engs[0].tokenize("crash me")
            cp = RequestCheckpoint(rows=[
                RowCheckpoint(
                    i, text_ids,
                    np.asarray(ref["tokens"][i][:6], np.int32),
                    False, 911 + i,
                )
                for i in range(2)
            ], chunk_index=3, site="r0", request_key=qkey)
            wire = to_wire(encode_checkpoint(
                cp, servers[0].resume_fingerprint,
            ))

            # the next dispatch goes to the replica with FEWER total
            # requests (the least-outstanding tie-break) — kill exactly
            # that one, so the request meets ECONNREFUSED first
            victim = min(
                range(2), key=lambda i: router.replicas[i].requests
            )
            live = 1 - victim
            servers[victim].shutdown(drain=False)
            # the supervisor hand-off already landed (crash recovery is
            # registry-consult-first; the parked-wait flavor is pinned
            # by TestRouterMigration.test_checkpoint_registry_*)
            status, out = _http("POST", port, "/admin/spool", {
                "replica": f"r{victim}",
                "checkpoints": {qkey: wire, "bad/key": wire},
            })
            assert status == 200 and out["ingested"] == 1  # bad key skipped

            status, payload = _http(
                "POST", port, "/generate", body, timeout=90,
            )
            assert status == 200
            assert payload["tokens"] == ref["tokens"]
            migs = {
                label: int(c.value)
                for label, c in router.registry.get(
                    "dalle_router_migrations_total"
                ).items()
            }
            assert migs.get("crash", 0) >= 1
            # the resuming replica restored the spooled prefixes
            assert int(engs[live].registry.get(
                "dalle_serving_resumed_tokens_total"
            ).value) == 12
        finally:
            front.shutdown()
            for i, s in enumerate(servers):
                s.shutdown()

    def test_request_key_header_round_trip(self):
        assert parse_request_key("abc-DEF_123.x") == "abc-DEF_123.x"
        assert parse_request_key(" padded ") == "padded"
        assert parse_request_key("bad/slash") is None
        assert parse_request_key("") is None
        assert parse_request_key(None) is None
        assert parse_request_key("x" * 65) is None

    def test_checkpoint_registry_bounds_and_waiters(self):
        reg = CheckpointRegistry(capacity=2)
        reg.put("a", "wa")
        reg.put("b", "wb")
        reg.put("c", "wc")  # evicts oldest
        assert reg.take("a") is None
        assert reg.take("b")["wire"] == "wb"
        assert reg.take("b") is None  # consumed at most once

        got = {}

        def waiter():
            got["e"] = reg.wait_for("k", timeout_s=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        reg.put("k", "wk", source="r9")
        t.join(timeout=5)
        assert got["e"]["wire"] == "wk" and got["e"]["source"] == "r9"
        assert reg.wait_for("nope", timeout_s=0.05) is None


# ------------------------------------------------------- supervisor spool


class TestSupervisorHandoff:
    def test_restart_hands_spool_to_router_and_clears(self, tmp_path):
        from dalle_pytorch_tpu.serving.supervisor import ReplicaSupervisor

        spool = CheckpointSpool(tmp_path)
        blob = encode_checkpoint(_cp(), "fp")

        posted = []

        class Proc:
            def __init__(self):
                self.returncode = None
                self._polls = 0

            def poll(self):
                return self.returncode

            def wait(self, timeout=None):
                if self.returncode is None:
                    raise __import__("subprocess").TimeoutExpired("x", 0.1)
                return self.returncode

            def terminate(self):
                self.returncode = 0

            def kill(self):
                self.returncode = -9

        procs = []

        def crash_after_journal(p):
            # the "child" journals its in-flight checkpoints (the beacon
            # would) AFTER the supervisor's first-boot stale-spool clear,
            # then dies abnormally
            time.sleep(0.2)
            spool.write({"key1": blob})
            p.returncode = 70

        def spawn():
            p = Proc()
            procs.append(p)
            if len(procs) == 1:
                threading.Thread(
                    target=crash_after_journal, args=(p,), daemon=True,
                ).start()
            return p

        sup = ReplicaSupervisor(
            ["fake"], spawn_fn=spawn, probe_fn=lambda: True,
            backoff_base_s=0.05, backoff_max_s=0.1,
            spool_dir=tmp_path, spool_notify_url="http://router:1",
            max_restarts=1,
        )
        sup._post_spool = lambda payload: posted.append(payload)

        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        deadline = time.monotonic() + 15
        while not posted and time.monotonic() < deadline:
            time.sleep(0.02)
        sup.stop()
        t.join(timeout=10)
        assert posted, "restart never handed the spool over"
        assert posted[0]["checkpoints"] == {"key1": to_wire(blob)}
        assert sup.spool_handoffs == 1
        assert spool.read() == {}  # cleared after a successful hand-off

    def test_first_boot_clears_stale_spool(self, tmp_path):
        from dalle_pytorch_tpu.serving.supervisor import ReplicaSupervisor

        spool = CheckpointSpool(tmp_path)
        spool.write({"stale": encode_checkpoint(_cp(), "fp")})

        class Proc:
            returncode = None

            def poll(self):
                return self.returncode

            def wait(self, timeout=None):
                if self.returncode is None:
                    raise __import__("subprocess").TimeoutExpired("x", 0.1)
                return self.returncode

            def terminate(self):
                self.returncode = 0

            def kill(self):
                self.returncode = -9

        posted = []
        sup = ReplicaSupervisor(
            ["fake"], spawn_fn=Proc, probe_fn=lambda: True,
            spool_dir=tmp_path, spool_notify_url="http://router:1",
        )
        sup._post_spool = lambda payload: posted.append(payload)
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while spool.read() and time.monotonic() < deadline:
            time.sleep(0.02)
        sup.stop()
        t.join(timeout=10)
        assert spool.read() == {}  # stale journal cleared, not handed over
        assert not posted


# ----------------------------------------------------------- spool beacon


class TestBeacon:
    def test_beacon_journals_at_cadence(self, toy, tmp_path):
        eng = _engine(toy, max_batch=2)
        spool = CheckpointSpool(tmp_path)
        batcher = ContinuousBatcher(
            eng, registry=eng.registry, spool=spool, spool_every=2,
        )
        batcher.checkpoint_fingerprint = "beacon-fp"
        try:
            req = _submit(batcher, _specs(1, seed=888))
            toks, _ = req.future.result(timeout=60)
            assert spool.writes >= 1
            assert batcher.last_beacon is not None
            # mid-flight beacons carried the in-flight request; decode
            # progressed between beacons, so SOME write held a prefix
            assert batcher.last_beacon["chunk_index"] > 0
        finally:
            batcher.shutdown(drain=False)
