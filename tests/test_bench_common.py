"""bench_common harness: profile fallback, OOM ladder, extras capture.

These tests guard the round-end contract: ONE JSON line on stdout no
matter how the workload fails (round-2 postmortem)."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bench_common import run_extra  # noqa: E402


def make_script(tmp_path, body):
    p = tmp_path / "fake_bench.py"
    p.write_text(body)
    return str(p)


def run_parent(tmp_path, script_body, parent_body):
    """Run a tiny parent that calls run_guarded on a fake child script."""
    child = make_script(tmp_path, script_body)
    parent = tmp_path / "parent.py"
    parent.write_text(
        "import sys\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        f"CHILD = {child!r}\n"
        "from bench_common import run_guarded\n" + parent_body
    )
    import os

    env = dict(os.environ)
    env["DALLE_TPU_FORCE_PLATFORM"] = "cpu"  # keep the device probe off
    # any tunneled accelerator backend
    env["BENCH_PROFILES_ON_CPU"] = "1"  # profiles are normally TPU-only
    proc = subprocess.run(
        [sys.executable, str(parent)], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {lines}"
    return json.loads(lines[0])


class TestProfiles:
    def test_profile_fallback_on_non_oom_failure(self, tmp_path):
        # child fails (ImportError-ish) unless FAKE_MODE=good
        script = (
            "import json, os, sys\n"
            "if os.environ.get('FAKE_MODE') != 'good':\n"
            "    sys.stderr.write('some crash, not memory related')\n"
            "    sys.exit(1)\n"
            "print(json.dumps({'metric': 'm', 'value': 1, 'unit': 'u',"
            " 'ok': True, 'vs_baseline': 1.0}))\n"
        )
        result = run_parent(
            tmp_path, script,
            "run_guarded('m', 'u', CHILD, child_timeout=60,\n"
            "    profiles=[('fast', {'FAKE_MODE': 'bad'}),"
            " ('safe', {'FAKE_MODE': 'good'})])\n",
        )
        assert result["ok"] is True
        assert result["profile"] == "safe"
        assert result["attempts"] == 2

    def test_oom_ladder_within_profile(self, tmp_path):
        # child OOMs unless BENCH_ACCUM >= 2
        script = (
            "import json, os, sys\n"
            "if int(os.environ.get('BENCH_ACCUM', '1')) < 2:\n"
            "    sys.stderr.write('RESOURCE_EXHAUSTED: out of memory')\n"
            "    sys.exit(1)\n"
            "print(json.dumps({'metric': 'm', 'value': 2, 'unit': 'u',"
            " 'ok': True, 'vs_baseline': 1.0}))\n"
        )
        result = run_parent(
            tmp_path, script,
            "def mb(env):\n"
            "    b = int(env.get('BENCH_BATCH', '16'))\n"
            "    a = int(env.get('BENCH_ACCUM', '1'))\n"
            "    return b // a if a > 0 and b % a == 0 else None\n"
            "run_guarded('m', 'u', CHILD, child_timeout=60,\n"
            "    oom_ladder=[{'BENCH_ACCUM': '2'}, {'BENCH_ACCUM': '4'}],\n"
            "    microbatch_of=mb)\n",
        )
        assert result["ok"] is True and result["value"] == 2
        assert result["attempts"] == 2

    def test_all_profiles_fail_is_one_failure_line(self, tmp_path):
        script = "import sys; sys.stderr.write('boom'); sys.exit(1)\n"
        result = run_parent(
            tmp_path, script,
            "run_guarded('m', 'u', CHILD, child_timeout=60,\n"
            "    profiles=[('a', {}), ('b', {})])\n",
        )
        assert result["ok"] is False and result["value"] == 0


class TestRunExtra:
    def test_captures_json_lines(self, tmp_path):
        script = make_script(
            tmp_path,
            "print('noise')\nprint('{\"a\": 1}')\nprint('{\"b\": 2}')\n",
        )
        out = tmp_path / "extra.jsonl"
        run_extra([sys.executable, script], str(out), "exp1", 60)
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert [r["result"] for r in recs] == [{"a": 1}, {"b": 2}]
        assert all(r["experiment"] == "exp1" for r in recs)

    def test_records_null_on_crash(self, tmp_path):
        script = make_script(tmp_path, "import sys; sys.exit(3)\n")
        out = tmp_path / "extra.jsonl"
        run_extra([sys.executable, script], str(out), "exp2", 60)
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert recs == [{"experiment": "exp2", "result": None}]
