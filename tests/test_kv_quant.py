"""int8 KV cache (`--kv_dtype int8`): quantization primitives, cache
layout (scale sidecars beside int8 payloads; default layout untouched),
the >=1.8x pool-capacity win over bf16, engine plumbing, partition rules
for the scale leaves, and the quality floor of a quantized decode
against the full-precision reference.

The default path carries the strongest pin: with `kv_dtype` unset the
state tree has NO scale leaves, K/V stay at the historical cache dtype,
and the continuous engine's tokens remain bit-identical to the
micro-batch engine's (PR 2's composition-invariance contract) — the
quantization plumbing must be invisible until opted into.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.models.attention import _kv_dequantize, _kv_quantize
from dalle_pytorch_tpu.models.dalle import (
    DALLE,
    init_paged_slot_state,
    init_slot_state,
)
from dalle_pytorch_tpu.parallel.serving_partition import decode_state_shardings
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.sharded import build_serving_mesh
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


def _model(**kw):
    base = dict(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    base.update(kw)
    return DALLE(**base)


def _params(model):
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, model.image_seq_len), jnp.int32)
    return jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)


def spec(seed, temperature=1.0, top_k=0.9):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:3] = (5, 6, 7)
    return SampleSpec(ids, seed=seed, temperature=temperature, top_k=top_k)


def _drain(eng, max_chunks=32):
    for _ in range(max_chunks):
        pos, act = eng.step_chunk()
        if (pos[act] >= eng.image_seq_len).all():
            return pos, act
    raise AssertionError("decode never finished")


def _attn(state):
    return state["cache"]["layer_0"]["attn"]


# ---------------------------------------------------------- primitives


class TestQuantPrimitives:
    def test_roundtrip_error_bounded_by_half_scale(self):
        """Symmetric rounding: every element round-trips within scale/2
        — the tolerance the quantized decode path inherits."""
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 5, 64)) * 4.0
        q, scale = _kv_quantize(x)
        assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
        err = np.abs(np.asarray(x, np.float32) - np.asarray(
            _kv_dequantize(q, scale)
        ))
        bound = 0.5 * np.asarray(scale)[..., None] + 1e-6
        assert (err <= bound).all()

    def test_zero_rows_round_trip_to_zero(self):
        """The eps clip keeps an all-zero (position, head) finite: zeros
        in, zeros out, no NaN from a 0/0 scale."""
        q, scale = _kv_quantize(jnp.zeros((1, 2, 3, 8)))
        dq = np.asarray(_kv_dequantize(q, scale))
        assert np.isfinite(dq).all() and (dq == 0).all()

    def test_extremes_use_the_full_int8_range(self):
        x = jnp.asarray([[[[-3.0, 0.0, 1.5, 3.0]]]])
        q, _ = _kv_quantize(x)
        qn = np.asarray(q)
        assert qn[..., 0] == -127 and qn[..., 3] == 127


# -------------------------------------------------------- cache layout


class TestCacheLayout:
    def test_default_layout_has_no_scale_leaves(self):
        model = _model()
        for state in (
            init_slot_state(model, 2),
            init_paged_slot_state(model, 2, n_pages=8, page_size=4),
        ):
            attn = _attn(state)
            assert "k_scale" not in attn and "v_scale" not in attn
            assert attn["k"].dtype != jnp.int8

    def test_int8_layout_pairs_payload_with_scales(self):
        model = _model().clone(kv_dtype="int8")
        slot = _attn(init_slot_state(model, 2))
        assert slot["k"].dtype == jnp.int8
        assert slot["k_scale"].dtype == jnp.float32
        assert slot["k_scale"].shape == slot["k"].shape[:-1]  # [B, H, S]
        paged = _attn(init_paged_slot_state(model, 2, n_pages=8, page_size=4))
        assert paged["k"].dtype == jnp.int8
        assert paged["v_scale"].shape == paged["v"].shape[:-1]  # [P, H, page]

    def test_capacity_ratio_vs_bf16_at_least_1p8(self):
        """The HBM win the ISSUE promises: at head-dim 64 an int8 page
        position costs D + 4 bytes (payload + fp32 scale) against bf16's
        2D — 2D/(D+4) = 1.88x rows in the same page budget. Derived from
        the REAL paged layout's leaf shapes/itemsizes, not re-stated
        constants."""
        model = _model(dim=128, heads=2, dim_head=64).clone(kv_dtype="int8")
        attn = _attn(init_paged_slot_state(model, 2, n_pages=4, page_size=4))
        d = attn["k"].shape[-1]
        int8_bytes = attn["k"].dtype.itemsize * d + attn["k_scale"].dtype.itemsize
        bf16_bytes = 2 * d  # the accelerator cache dtype's cost per position
        assert bf16_bytes / int8_bytes >= 1.8


# ----------------------------------------------------- partition rules


class TestScalePartitionRules:
    def test_scales_follow_their_payloads_head_split(self):
        """k_scale/v_scale shard exactly like k/v: head axis over tp,
        page/batch axes whole — a scale on a different device than its
        payload would force a collective inside the decode kernel."""
        mesh = build_serving_mesh({"tp": 2})
        model = _model().clone(kv_dtype="int8")

        def flat(state):
            return {
                "/".join(str(getattr(p, "key", p)) for p in path): s.spec
                for path, s in jax.tree_util.tree_flatten_with_path(
                    decode_state_shardings(state, mesh)
                )[0]
            }

        slot = flat(init_slot_state(model, 4))
        assert next(
            v for p, v in slot.items() if p.endswith("attn/k_scale")
        ) == P(None, "tp")  # [B, H, S]
        paged = flat(init_paged_slot_state(model, 4, n_pages=8, page_size=4))
        assert next(
            v for p, v in paged.items() if p.endswith("attn/v_scale")
        ) == P(None, "tp")  # [P, H, page]: page axis stays whole


# ----------------------------------------------------- engine plumbing


@pytest.fixture(scope="module")
def toy():
    model = _model()
    return model, _params(model)


class TestEnginePlumbing:
    def test_engine_clones_model_and_reports_dtype(self, toy):
        model, params = toy
        eng = PagedContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            page_size=4, kv_dtype="int8", registry=MetricsRegistry(),
        )
        assert eng.model.kv_dtype == "int8"
        det = eng.kv_detail()
        assert det["dtype"] == "int8"
        assert det["bytes_per_page"] == eng.kv_page_bytes()
        assert "k_scale" in _attn(eng._state)
        assert eng.registry.get(
            "dalle_serving_kv_bytes_per_slot"
        ).value == eng.kv_bytes_per_slot() > 0

    def test_default_engine_unchanged(self, toy):
        model, params = toy
        eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            registry=MetricsRegistry(),
        )
        assert getattr(eng.model, "kv_dtype", None) is None
        assert "k_scale" not in _attn(eng._state)


# ------------------------------------------------------------- quality


class TestDecodeQuality:
    def test_default_path_bit_identical_to_micro(self, toy):
        """The bf16/default pin: with kv_dtype unset, the continuous
        engine's tokens stay BIT-IDENTICAL to the micro-batch engine's
        (composition invariance) — the int8 plumbing changed nothing it
        wasn't asked to."""
        model, params = toy
        micro = GenerationEngine(
            model=model, variables=params, batch_shapes=(2,),
            registry=MetricsRegistry(),
        )
        cont = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            registry=MetricsRegistry(),
        )
        specs = [spec(51, 0.9, 0.9), spec(53, 1.1, 0.85)]
        ref, _ = micro.generate(specs)
        for i, s in enumerate(specs):
            cont.prefill_slot(i, s)
        _drain(cont)
        got = cont.harvest([0, 1])
        cont.release([0, 1])
        np.testing.assert_array_equal(np.asarray(ref), got)

    def test_int8_tokens_track_the_reference(self, toy):
        """Tolerance pin for the quantized path ONLY: int8 decode is NOT
        bit-identical by design (scale/2 rounding in every attention
        read) — but on the toy model the token stream must stay close to
        the full-precision decode. The bound is deliberately loose;
        quality is measured properly (CLIP, full-size model) by
        bench_serving.py's quality block."""
        model, params = toy
        ref_eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            registry=MetricsRegistry(),
        )
        q_eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            kv_dtype="int8", registry=MetricsRegistry(),
        )
        specs = [spec(61, 0.9, 0.9), spec(63, 1.1, 0.85)]
        outs = []
        for eng in (ref_eng, q_eng):
            for i, s in enumerate(specs):
                eng.prefill_slot(i, s)
            _drain(eng)
            outs.append(eng.harvest([0, 1]))
            eng.release([0, 1])
        agreement = float(np.mean(outs[0] == outs[1]))
        assert agreement >= 0.75, f"token agreement {agreement:.3f}"
