"""Thread-model tracelint rules (TL013-TL016), the historical-bug
regression corpus, the incremental `--watch` cache, and the rule
selection/timing CLI contracts.

The regression corpus reconstructs the four concurrency bugs this repo
actually shipped and fixed by hand in review (PR 7 sampler iteration,
PR 9 collector read, PR 9 exporter counters, PR 14 export-withdraw
claim) — each must be flagged by the new rules, and each shipped fix
must lint clean. The package-stays-clean gate in tests/test_analysis.py
covers the new rules automatically (they are in ALL_RULES).
"""

import json
import textwrap
from pathlib import Path

import pytest

from dalle_pytorch_tpu.analysis import PACKAGE_DIR, lint_paths, main
from dalle_pytorch_tpu.analysis.watch import LintCache, watch_paths

FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def codes(result):
    return [f.rule for f in result.findings]


# ------------------------------------------------------------ rule corpus


class TestThreadRuleCorpus:
    @pytest.mark.parametrize(
        "fixture, code, expected",
        [
            ("threads/tl013_pos.py", "TL013", 3),
            ("threads/tl014_pos.py", "TL014", 3),
            ("threads/tl015_pos.py", "TL015", 2),
            ("serving/tl016_pos.py", "TL016", 3),
        ],
    )
    def test_positive_fixture_caught(self, fixture, code, expected):
        result = lint_paths([FIXTURES / fixture])
        got = codes(result)
        assert got.count(code) == expected, (
            f"{fixture}: expected {expected} {code} findings, got {got}"
        )
        assert all(c == code for c in got), (
            f"{fixture}: unexpected extra findings {got}"
        )

    @pytest.mark.parametrize(
        "fixture",
        [
            "threads/tl013_neg.py",
            "threads/tl014_neg.py",
            "threads/tl015_neg.py",
            "serving/tl016_neg.py",
        ],
    )
    def test_negative_fixture_clean(self, fixture):
        result = lint_paths([FIXTURES / fixture])
        assert result.clean, (
            f"{fixture} should be clean, got: "
            + "; ".join(f.render() for f in result.findings)
        )


class TestRegressionCorpus:
    """The four known past concurrency bugs, reconstructed: the new
    rules must flag each buggy shape, and the shipped fix stays clean."""

    @pytest.mark.parametrize(
        "fixture, expected",
        [
            ("pr7_sampler_pos.py", ["TL014"]),
            ("pr9_collector_pos.py", ["TL014"]),
            ("pr9_exporter_pos.py", ["TL013", "TL013"]),
            ("pr14_claim_pos.py", ["TL013"]),
        ],
    )
    def test_historical_bug_flagged(self, fixture, expected):
        result = lint_paths([FIXTURES / "threads" / fixture])
        assert sorted(codes(result)) == sorted(expected), (
            f"{fixture}: " + "; ".join(f.render() for f in result.findings)
        )

    @pytest.mark.parametrize(
        "fixture",
        [
            "pr7_sampler_neg.py",
            "pr9_collector_neg.py",
            "pr9_exporter_neg.py",
            "pr14_claim_neg.py",
        ],
    )
    def test_shipped_fix_clean(self, fixture):
        result = lint_paths([FIXTURES / "threads" / fixture])
        assert result.clean, "; ".join(f.render() for f in result.findings)


# ------------------------------------------------------- model behaviors


UNMARKED_SHARED = textwrap.dedent(
    """\
    import threading

    class Collector:
        def __init__(self):
            self._lock = threading.Lock()
            self._traces = {}

        def ingest(self, rec):
            with self._lock:
                self._traces[rec["id"]] = rec

        def traces(self):
            return [t for t in self._traces.values()]
    """
)


class TestThreadModel:
    def test_threads_marker_promotes_public_methods_to_roots(self, tmp_path):
        """A class with no worker thread has one (collective) caller and
        stays silent; `# tracelint: threads` declares the handler fan-in
        and the same code flags."""
        f = tmp_path / "plain.py"
        f.write_text(UNMARKED_SHARED)
        assert lint_paths([f]).clean
        g = tmp_path / "marked.py"
        g.write_text(
            UNMARKED_SHARED.replace(
                "class Collector:", "# tracelint: threads\nclass Collector:"
            )
        )
        assert codes(lint_paths([g])) == ["TL014"]

    def test_plain_flag_rebind_exempt_but_checked_act_flagged(self, tmp_path):
        """`self._running = False` from stop() is the GIL-atomic flag
        idiom (exempt); the same store becomes a finding once the worker
        check-then-acts on the attribute lock-free."""
        base = textwrap.dedent(
            """\
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._running = True
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while self._running:
                        pass

                def stop(self):
                    self._running = False
            """
        )
        f = tmp_path / "flag.py"
        f.write_text(base)
        assert lint_paths([f]).clean
        claim = base.replace(
            "while self._running:\n            pass",
            "if self._running:\n            self._running = False",
        )
        assert claim != base
        g = tmp_path / "claim.py"
        g.write_text(claim)
        assert codes(lint_paths([g])) == ["TL013"]

    def test_inherited_lock_through_private_helper(self, tmp_path):
        """The `_viable_head` convention: a private helper whose every
        call site holds the lock runs under it; making ONE call site
        lock-free breaks the inheritance and the finding appears."""
        locked = textwrap.dedent(
            """\
            import threading

            class B:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._n = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        with self._cond:
                            self._bump()

                def _bump(self):
                    self._n += 1

                def total(self):
                    with self._cond:
                        return self._n
            """
        )
        f = tmp_path / "locked.py"
        f.write_text(locked)
        assert lint_paths([f]).clean
        leaky = locked.replace(
            "with self._cond:\n                self._bump()",
            "self._bump()",
        )
        assert leaky != locked
        g = tmp_path / "leaky.py"
        g.write_text(leaky)
        assert codes(lint_paths([g])) == ["TL013"]

    def test_annotated_lock_binding_recognized(self, tmp_path):
        """`self._lock: threading.Lock = threading.Lock()` (AnnAssign)
        binds the lock like the plain form — correctly guarded code must
        not read as unguarded (code-review regression)."""
        f = tmp_path / "annotated.py"
        f.write_text(textwrap.dedent(
            """\
            import threading

            class W:
                def __init__(self):
                    self._lock: threading.Lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        with self._lock:
                            self._n += 1

                def total(self):
                    with self._lock:
                        return self._n
            """
        ))
        assert lint_paths([f]).clean, [
            x.render() for x in lint_paths([f]).findings
        ]

    def test_tl016_exempts_init(self, tmp_path):
        """A blocking call under a lock in `__init__` cannot contend
        with anything — construction happens-before thread start, the
        same exemption the access index applies (code-review
        regression). The identical call in a post-construction method
        still fires."""
        serving = tmp_path / "serving"
        serving.mkdir()
        f = serving / "boot.py"
        f.write_text(textwrap.dedent(
            """\
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    with self._lock:
                        time.sleep(0.1)
            """
        ))
        assert lint_paths([f]).clean
        g = serving / "live.py"
        g.write_text(textwrap.dedent(
            """\
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def step(self):
                    with self._lock:
                        time.sleep(0.1)
            """
        ))
        assert codes(lint_paths([g])) == ["TL016"]

    def test_condition_aliases_wrapped_lock(self, tmp_path):
        """`Condition(self._lock)` acquires the SAME mutex as
        `with self._lock:` — a write under one and a read under the
        other share a lock and stay clean (the router's `_drained`
        idiom)."""
        f = tmp_path / "alias.py"
        f.write_text(textwrap.dedent(
            """\
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._drained = threading.Condition(self._lock)
                    self._outstanding = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        with self._drained:
                            self._outstanding += 1

                def outstanding(self):
                    with self._lock:
                        return self._outstanding
            """
        ))
        assert lint_paths([f]).clean

    def test_tl015_cycle_crosses_files(self, tmp_path):
        """TL015 is package-scope: the two halves of an inversion can
        live in different modules (same class, methods split across
        files) and the graph still closes the cycle."""
        (tmp_path / "one.py").write_text(textwrap.dedent(
            """\
            import threading

            class R:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass
            """
        ))
        (tmp_path / "two.py").write_text(textwrap.dedent(
            """\
            import threading

            class R:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """
        ))
        result = lint_paths([tmp_path])
        assert codes(result) == ["TL015"]
        # each file alone is order-consistent
        assert lint_paths([tmp_path / "one.py"]).clean
        assert lint_paths([tmp_path / "two.py"]).clean

    def test_tl016_scoped_to_serving_and_obs(self, tmp_path):
        """The same sleep-under-lock outside serving//obs/ is out of
        scope — training scripts hold no latency-critical locks."""
        src = textwrap.dedent(
            """\
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def step(self):
                    with self._lock:
                        time.sleep(0.1)
            """
        )
        outside = tmp_path / "elsewhere.py"
        outside.write_text(src)
        assert lint_paths([outside]).clean
        obs = tmp_path / "obs"
        obs.mkdir()
        inside = obs / "sampler.py"
        inside.write_text(src)
        assert codes(lint_paths([inside])) == ["TL016"]

    def test_reasoned_suppression_silences_tl013(self, tmp_path):
        f = tmp_path / "justified.py"
        f.write_text(textwrap.dedent(
            """\
            import threading

            class W:
                def __init__(self):
                    self._n = 0
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    while True:
                        self._n += 1  # tracelint: disable=TL013 -- fixture: stat is advisory, torn reads acceptable

                def total(self):
                    return self._n
            """
        ))
        result = lint_paths([f])
        assert result.clean and len(result.suppressed) == 1


# ------------------------------------------------- incremental lint cache


class TestIncrementalCache:
    def _seed(self, tmp_path):
        (tmp_path / "a.py").write_text("def a():\n    return 1\n")
        (tmp_path / "b.py").write_text("def b():\n    breakpoint()\n")
        (tmp_path / "c.py").write_text("def c():\n    return 3\n")

    def test_single_edit_reparses_only_that_file(self, tmp_path):
        """The acceptance pin: a re-lint after one edit re-parses ONE
        file; the others hit both the AST and the finding cache."""
        self._seed(tmp_path)
        cache = LintCache()
        first = lint_paths([tmp_path], cache=cache)
        assert first.cache == {
            "files": 3, "reparsed": 3, "ast_hits": 0, "finding_hits": 0,
        }
        again = lint_paths([tmp_path], cache=cache)
        assert again.cache == {
            "files": 3, "reparsed": 0, "ast_hits": 3, "finding_hits": 3,
        }
        (tmp_path / "a.py").write_text("def a():\n    return 2\n")
        third = lint_paths([tmp_path], cache=cache)
        assert third.cache == {
            "files": 3, "reparsed": 1, "ast_hits": 2, "finding_hits": 2,
        }
        # findings identical across cached and fresh runs
        assert codes(third) == codes(lint_paths([tmp_path])) == ["TL006"]

    def test_touch_without_content_change_is_a_hit(self, tmp_path):
        """The cache keys on CONTENT, not mtime: rewriting identical
        bytes re-parses nothing."""
        self._seed(tmp_path)
        cache = LintCache()
        lint_paths([tmp_path], cache=cache)
        (tmp_path / "b.py").write_text("def b():\n    breakpoint()\n")
        again = lint_paths([tmp_path], cache=cache)
        assert again.cache["reparsed"] == 0

    def test_cross_file_fact_change_invalidates_findings_not_parses(
        self, tmp_path
    ):
        """An edit that changes the donation registry re-runs every
        file's rules (stale TL003 state) but still re-parses only the
        edited file."""
        (tmp_path / "dispatch.py").write_text(textwrap.dedent(
            """\
            def _chunk_builder(model, key):
                def fn(state):
                    return state
                return fn

            def _jit_sample(builder, model, key, *args):
                return builder(model, key)(*args)

            def chunk(state):
                return _jit_sample(_chunk_builder, None, (), state)
            """
        ))
        (tmp_path / "caller.py").write_text(textwrap.dedent(
            """\
            from dispatch import chunk

            def serve(state):
                new = chunk(state)
                return state["img_pos"]
            """
        ))
        cache = LintCache()
        first = lint_paths([tmp_path], cache=cache)
        assert first.clean  # no donation tag yet: caller.py is clean
        src = (tmp_path / "dispatch.py").read_text()
        (tmp_path / "dispatch.py").write_text(
            src.replace(
                "def _jit_sample",
                "_chunk_builder._donate_argnums = (0,)\n\ndef _jit_sample",
            )
        )
        second = lint_paths([tmp_path], cache=cache)
        assert second.cache["reparsed"] == 1
        assert second.cache["finding_hits"] == 0  # registry changed
        assert codes(second) == ["TL003"]
        assert second.findings[0].path.endswith("caller.py")

    def test_watch_loop_emits_one_json_doc_per_event(self, tmp_path):
        import io

        self._seed(tmp_path)
        edits = iter([
            None,
            lambda: (tmp_path / "a.py").write_text("import ipdb\n"),
        ])

        def sleeper(_s):
            e = next(edits, None)
            if callable(e):
                e()

        out = io.StringIO()
        rc = watch_paths(
            [tmp_path], fmt="json", max_events=2, stream=out,
            sleep_fn=sleeper, poll_s=0.01,
        )
        assert rc == 1
        docs, cur = [], []
        for line in out.getvalue().splitlines():
            cur.append(line)
            if line == "}":
                docs.append(json.loads("\n".join(cur)))
                cur = []
        assert len(docs) == 2
        assert [f["rule"] for f in docs[0]["findings"]] == ["TL006"]
        assert sorted(f["rule"] for f in docs[1]["findings"]) == [
            "TL006", "TL006",
        ]
        # event 2 is incremental: one reparse, and the per-event JSON
        # carries the cache counters + per-rule wall times
        assert docs[1]["cache"]["reparsed"] == 1
        assert docs[1]["rule_times_ms"]


# ----------------------------------------------------- CLI flag contracts


class TestSelectionFlags:
    def test_rules_alias_selects(self):
        assert main(
            [str(FIXTURES / "threads" / "tl013_pos.py"), "--rules", "TL006"]
        ) == 0
        assert main(
            [str(FIXTURES / "threads" / "tl013_pos.py"), "--rules", "TL013"]
        ) == 1

    def test_exclude_rules_drops_only_named(self):
        target = str(FIXTURES / "threads" / "tl013_pos.py")
        assert main([target, "--exclude-rules", "TL013"]) == 0
        assert main([target, "--exclude-rules", "TL014"]) == 1

    def test_exclude_unknown_rule_is_usage_error(self):
        assert main(["--exclude-rules", "TL999"]) == 2

    def test_rule_times_in_json(self, tmp_path, capsys):
        f = tmp_path / "x.py"
        f.write_text("def a():\n    return 1\n")
        main([str(f), "--format", "json"])
        payload = json.loads(capsys.readouterr().out)
        times = payload["rule_times_ms"]
        assert "TL013" in times and "TL015" in times
        assert all(t >= 0 for t in times.values())
        # restricted runs time only the selected rules
        main([str(f), "--format", "json", "--rules", "TL013"])
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["rule_times_ms"]) == {"TL013"}


# ------------------------------------------------------- pre-commit gate


def test_precommit_entry_point_clean_on_package_files():
    """The pre-commit hook calls the `dalle-tpu-lint` console script
    (analysis.lint:main) with the staged .py files as EXPLICIT
    arguments — which skips the shipped baseline by design. The shipped
    package must exit 0 through that exact path, new rules included."""
    staged = sorted(
        str(p)
        for sub in ("serving", "obs", "analysis")
        for p in (PACKAGE_DIR / sub).glob("*.py")
    )
    assert staged, "package layout changed?"
    assert main(staged) == 0
