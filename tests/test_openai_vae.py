"""Pure-XLA OpenAI dVAE converter (models/vae_io.py `_OpenAIGraph`) vs. a
torch golden model.

The reference runs the downloaded dall_e encoder/decoder modules through
torch on GPU (`/root/reference/dalle_pytorch/vae.py:111-157`); our
framework converts the pickles into jitted NHWC XLA graphs so the frozen-
VAE encode stays on chip. Since the real pickles need network egress, the
test reconstructs the dall_e architecture in torch (CPU) with the package's
exact module/param naming (custom Conv2d with `w`/`b` params, `blocks.*`
Sequential layout, post_gain residual scaling), saves synthetic pickles,
and checks encode indices + decode images agree between torch and XLA.
"""

import math
from collections import OrderedDict

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn
import torch.nn.functional as F

import jax.numpy as jnp

from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE, _OpenAIGraph


# ---------------------------------------------------------------- torch golden
# Mirrors dall_e/{utils,encoder,decoder}.py structure (public architecture):
# custom conv module whose parameters are literally named `w` and `b`.


class DConv(nn.Module):
    def __init__(self, n_in, n_out, kw):
        super().__init__()
        self.kw = kw
        self.w = nn.Parameter(torch.randn(n_out, n_in, kw, kw) * 0.2)
        self.b = nn.Parameter(torch.randn(n_out) * 0.1)

    def forward(self, x):
        return F.conv2d(x, self.w, self.b, padding=(self.kw - 1) // 2)


def _enc_block(n_in, n_out, n_layers):
    n_hid = n_out // 4
    block = nn.Module()
    block.post_gain = 1 / (n_layers ** 2)
    block.id_path = DConv(n_in, n_out, 1) if n_in != n_out else nn.Identity()
    block.res_path = nn.Sequential(OrderedDict([
        ("relu_1", nn.ReLU()), ("conv_1", DConv(n_in, n_hid, 3)),
        ("relu_2", nn.ReLU()), ("conv_2", DConv(n_hid, n_hid, 3)),
        ("relu_3", nn.ReLU()), ("conv_3", DConv(n_hid, n_hid, 3)),
        ("relu_4", nn.ReLU()), ("conv_4", DConv(n_hid, n_out, 1)),
    ]))
    block.forward = lambda x, b=block: (
        (b.id_path(x) if not isinstance(b.id_path, nn.Identity) else x)
        + b.post_gain * b.res_path(x)
    )
    return block


def _dec_block(n_in, n_out, n_layers):
    n_hid = n_out // 4
    block = nn.Module()
    block.post_gain = 1 / (n_layers ** 2)
    block.id_path = DConv(n_in, n_out, 1) if n_in != n_out else nn.Identity()
    block.res_path = nn.Sequential(OrderedDict([
        ("relu_1", nn.ReLU()), ("conv_1", DConv(n_in, n_hid, 1)),
        ("relu_2", nn.ReLU()), ("conv_2", DConv(n_hid, n_hid, 3)),
        ("relu_3", nn.ReLU()), ("conv_3", DConv(n_hid, n_hid, 3)),
        ("relu_4", nn.ReLU()), ("conv_4", DConv(n_hid, n_out, 3)),
    ]))
    block.forward = lambda x, b=block: (
        (b.id_path(x) if not isinstance(b.id_path, nn.Identity) else x)
        + b.post_gain * b.res_path(x)
    )
    return block


class TEncoder(nn.Module):
    def __init__(self, n_hid=8, vocab=32, groups=4, blk=1, channels=3):
        super().__init__()
        n_layers = groups * blk
        widths = [1, 1, 2, 4, 8][: groups + 1]
        seq = [("input", DConv(channels, widths[1] * n_hid, 7))]
        for g in range(1, groups + 1):
            items = []
            for i in range(1, blk + 1):
                n_in = widths[g if i > 1 else g - 1] * n_hid
                if g == 1 and i == 1:
                    n_in = widths[1] * n_hid
                items.append(
                    (f"block_{i}", _enc_block(n_in, widths[g] * n_hid, n_layers))
                )
            if g != groups:
                items.append(("pool", nn.MaxPool2d(kernel_size=2)))
            seq.append((f"group_{g}", nn.Sequential(OrderedDict(items))))
        seq.append(("output", nn.Sequential(OrderedDict([
            ("relu", nn.ReLU()), ("conv", DConv(widths[groups] * n_hid, vocab, 1)),
        ]))))
        self.blocks = nn.Sequential(OrderedDict(seq))

    def forward(self, x):
        return self.blocks(x)


class TDecoder(nn.Module):
    def __init__(self, n_hid=8, n_init=16, vocab=32, groups=4, blk=1, channels=3):
        super().__init__()
        n_layers = groups * blk
        widths = [8, 8, 4, 2, 1][: groups + 1]
        seq = [("input", DConv(vocab, n_init, 1))]
        for g in range(1, groups + 1):
            items = []
            for i in range(1, blk + 1):
                n_in = n_init if (g == 1 and i == 1) else (
                    widths[g if i > 1 else g - 1] * n_hid
                )
                items.append(
                    (f"block_{i}", _dec_block(n_in, widths[g] * n_hid, n_layers))
                )
            if g != groups:
                items.append(
                    ("upsample", nn.Upsample(scale_factor=2, mode="nearest"))
                )
            seq.append((f"group_{g}", nn.Sequential(OrderedDict(items))))
        seq.append(("output", nn.Sequential(OrderedDict([
            ("relu", nn.ReLU()),
            ("conv", DConv(widths[groups] * n_hid, 2 * channels, 1)),
        ]))))
        self.blocks = nn.Sequential(OrderedDict(seq))

    def forward(self, x):
        return self.blocks(x)


VOCAB = 32


@pytest.fixture(scope="module")
def vae(tmp_path_factory):
    torch.manual_seed(0)
    cache = tmp_path_factory.mktemp("openai_vae")
    enc, dec = TEncoder(vocab=VOCAB), TDecoder(vocab=VOCAB)
    torch.save(enc.state_dict(), cache / "encoder.pkl")
    torch.save(dec.state_dict(), cache / "decoder.pkl")
    v = OpenAIDiscreteVAE(cache_dir=cache)
    return v, enc, dec


class TestOpenAIConverter:
    def test_encode_matches_torch(self, vae):
        v, enc, _ = vae
        rng = np.random.RandomState(0)
        imgs = rng.rand(2, 32, 32, 3).astype(np.float32)
        with torch.no_grad():
            x = torch.from_numpy(
                np.asarray(v.map_pixels(imgs)).transpose(0, 3, 1, 2)
            )
            golden = torch.argmax(enc(x), dim=1).flatten(1).numpy()
        ours = np.asarray(v.get_codebook_indices(jnp.asarray(imgs)))
        assert ours.shape == golden.shape == (2, 16)  # f/8: 32px -> 4x4
        agree = (ours == golden).mean()
        assert agree > 0.95, f"only {agree:.0%} of indices agree with torch"

    def test_decode_matches_torch(self, vae):
        v, _, dec = vae
        rng = np.random.RandomState(1)
        seq = rng.randint(0, VOCAB, (2, 16)).astype(np.int32)
        with torch.no_grad():
            z = F.one_hot(torch.from_numpy(seq).long(), num_classes=VOCAB)
            z = z.view(2, 4, 4, VOCAB).permute(0, 3, 1, 2).float()
            out = torch.sigmoid(dec(z)[:, :3])
            golden = np.asarray(
                v.unmap_pixels(jnp.asarray(out.permute(0, 2, 3, 1).numpy()))
            )
        ours = np.asarray(v.decode(jnp.asarray(seq)))
        assert ours.shape == (2, 32, 32, 3)
        np.testing.assert_allclose(ours, golden, rtol=1e-3, atol=1e-4)

    def test_no_torch_in_hot_path(self, vae):
        """The VERDICT criterion: encode/decode must be pure XLA."""
        import inspect

        v, _, _ = vae
        for fn in (
            OpenAIDiscreteVAE.get_codebook_indices,
            OpenAIDiscreteVAE.decode,
            _OpenAIGraph.encode_logits,
            _OpenAIGraph.decode_pixels,
        ):
            assert "torch" not in inspect.getsource(fn)
        # jit-compiled callables exist and run without torch tensors
        idx = v.get_codebook_indices(jnp.zeros((1, 32, 32, 3)))
        assert idx.dtype == jnp.int32

    def test_accepts_weight_bias_naming(self, vae):
        """Pickles that use standard .weight/.bias keys convert too."""
        v, enc, dec = vae
        def rename(sd):
            out = {}
            for k, val in sd.items():
                if k.endswith(".w"):
                    k = k[:-2] + ".weight"
                elif k.endswith(".b"):
                    k = k[:-2] + ".bias"
                out[k] = val
            return out
        g = _OpenAIGraph(
            rename(enc.state_dict()), rename(dec.state_dict())
        )
        imgs = jnp.zeros((1, 32, 32, 3)) + 0.5
        logits = g.encode_logits(g.enc, OpenAIDiscreteVAE.map_pixels(imgs))
        ref = v._encode_jit(v._graph.enc, OpenAIDiscreteVAE.map_pixels(imgs))
        np.testing.assert_array_equal(
            np.asarray(jnp.argmax(logits, -1).reshape(1, -1)), np.asarray(ref.reshape(1, -1))
        )

# ------------------------------------------------- released geometry (f/8)


@pytest.mark.slow
class TestReleasedGeometry:
    """Structural golden at the published dall_e geometry.

    The toy tests above prove the conversion math at vocab 32; this pins
    the importer to the released model shape — n_hid 256, 4 groups x 2
    blocks (so post_gain 1/64), vocab 8192, decoder n_init 128, 2x3
    output channels — so a naming/structural mismatch against the real
    encoder.pkl/decoder.pkl state-dict layout fails here rather than at
    load time (`/root/reference/dalle_pytorch/vae.py:111-157`). The real
    *weights* cannot be fetched in this egress-less environment
    (documented limitation, BASELINE.md); spatial extent is reduced to
    32px — state-dict structure is resolution-independent.
    """

    @pytest.fixture(scope="class")
    def released(self, tmp_path_factory):
        torch.manual_seed(0)
        cache = tmp_path_factory.mktemp("openai_vae_full")
        enc = TEncoder(n_hid=256, vocab=8192, groups=4, blk=2)
        dec = TDecoder(n_hid=256, n_init=128, vocab=8192, groups=4, blk=2)
        torch.save(enc.state_dict(), cache / "encoder.pkl")
        torch.save(dec.state_dict(), cache / "decoder.pkl")
        from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE as V

        return V(cache_dir=cache), enc, dec

    def test_inferred_geometry(self, released):
        v, enc, _ = released
        assert v.num_tokens == 8192
        assert v.num_layers == 3  # f/8: three maxpools between four groups
        # released channel progression: input conv 256, groups 256/512/1024/2048
        sd = enc.state_dict()
        assert sd["blocks.input.w"].shape == (256, 3, 7, 7)
        assert sd["blocks.group_4.block_1.res_path.conv_4.w"].shape[0] == 2048

    def test_released_state_dict_parity(self, released):
        v, enc, dec = released
        rng = np.random.RandomState(0)
        imgs = rng.rand(1, 32, 32, 3).astype(np.float32)
        with torch.no_grad():
            x = torch.from_numpy(
                np.asarray(v.map_pixels(imgs)).transpose(0, 3, 1, 2)
            )
            golden = torch.argmax(enc(x), dim=1).flatten(1).numpy()
        ours = np.asarray(v.get_codebook_indices(jnp.asarray(imgs)))
        assert ours.shape == golden.shape == (1, 16)  # 32px / f8 = 4x4
        agree = (ours == golden).mean()
        assert agree > 0.9, f"only {agree:.0%} of indices agree with torch"

        seq = rng.randint(0, 8192, (1, 16)).astype(np.int32)
        with torch.no_grad():
            import torch.nn.functional as TF

            z = TF.one_hot(torch.from_numpy(seq).long(), num_classes=8192)
            z = z.view(1, 4, 4, 8192).permute(0, 3, 1, 2).float()
            out = torch.sigmoid(dec(z)[:, :3])
            golden_img = np.asarray(
                v.unmap_pixels(jnp.asarray(out.permute(0, 2, 3, 1).numpy()))
            )
        ours_img = np.asarray(v.decode(jnp.asarray(seq)))
        assert ours_img.shape == (1, 32, 32, 3)
        np.testing.assert_allclose(ours_img, golden_img, rtol=1e-3, atol=1e-3)
