"""Streaming /generate: SSE codec, per-request event streams, progressive
previews, and fleet-safe re-attach across preemption, migration, and
failover.

The load-bearing contracts:

  * CHUNK EVENTS ARE GAPLESS AND DUPLICATE-FREE, FLEET-WIDE — progress is
    content-addressed (a request-level chunk index with a monotonic high
    water in `RequestStream`, plus a second high water in the router's
    stream splice across replica seams), so a preemption resume, a
    drain-migration re-dispatch, and a from-scratch failover re-decode
    all replay silently: the client sees every chunk exactly once, in
    order, on one continuous stream.
  * A PREVIEW IS ONE EXTRA WARMED PROGRAM — `preview_enabled=True` adds
    exactly the `preview` entry to the program ladder, and a warm
    streaming cycle (admit, chunks, snapshot + preview fill-decode,
    harvest, release) compiles NOTHING after warmup.
  * A DISCONNECTED CLIENT CANCELS ITS DECODE — the SSE writer's broken
    pipe cancels the request, and the batcher's `_reap` frees its slots
    at the next chunk boundary instead of decoding for nobody.
  * A STREAMED REQUEST IS THE SAME REQUEST — terminal `result` tokens are
    bit-identical to the buffered (non-streaming) run of the same body.
"""

import io
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.obs.logging import StructuredLog
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.faults import FaultInjector
from dalle_pytorch_tpu.serving.router import FleetRouter, RouterServer
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.serving.streaming import (
    TERMINAL_TYPES,
    RequestStream,
    SSEParser,
    StreamRegistry,
    encode_sse,
)
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP
CHUNK = 4
N_CHUNKS = IMG_SEQ // CHUNK


@pytest.fixture(scope="module")
def toy():
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE

    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    # previews need a real pixel decode: a tiny dVAE with a matching
    # codebook (4x4 grid -> 16x16 images)
    vae = DiscreteVAE(
        image_size=4 * FMAP, num_layers=2, num_tokens=32,
        codebook_dim=16, hidden_dim=8,
    )
    vae_params = jax.jit(vae.init)(
        jax.random.PRNGKey(1), jnp.zeros((1, 4 * FMAP, 4 * FMAP, 3))
    )["params"]
    return model, params, vae, vae_params


def _engine(toy, preview=True, paged=False, max_batch=2, chunk_tokens=CHUNK,
            **kw):
    model, params, vae, vae_params = toy
    cls = PagedContinuousEngine if paged else ContinuousEngine
    if paged:
        kw.setdefault("page_size", 4)
    eng = cls(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        max_batch=max_batch,
        chunk_tokens=chunk_tokens, prefill_batch=max_batch,
        registry=MetricsRegistry(), preview_enabled=preview, **kw,
    )
    eng.tokenizer = ByteTokenizer()
    return eng


def _server(toy, preview_every=1, **kw):
    eng = _engine(toy)
    return eng, ServingServer(
        eng, port=0, request_timeout_s=60, preview_every=preview_every, **kw
    ).start()


# ----------------------------------------------------------- wire format


class TestSSECodec:
    def test_round_trip_including_split_chunks(self):
        frames = (
            encode_sse("open", {"request_key": "k1", "cursor": 0})
            + encode_sse("progress", {"chunk": 1, "tokens": 4}, seq=0)
            + b": keep-alive\n\n"
            + encode_sse("result", {"tokens": [[1, 2]]}, seq=1)
        )
        parser = SSEParser()
        events = []
        # worst-case delivery: one byte at a time across reads
        for i in range(0, len(frames), 3):
            events.extend(parser.feed(frames[i:i + 3]))
        assert [e[0] for e in events] == ["open", "progress", "result"]
        assert events[0][2] is None  # open carries no id:
        assert events[1][1]["chunk"] == 1 and events[1][2] == 0
        assert events[2][2] == 1
        assert events[2][0] in TERMINAL_TYPES

    def test_non_json_data_degrades_to_raw(self):
        parser = SSEParser()
        events = parser.feed(b"event: weird\ndata: not json\n\n")
        assert events == [("weird", {"raw": "not json"}, None)]


class TestRequestStream:
    def test_progress_high_water_swallows_replays(self):
        s = RequestStream(key="k")
        assert s.progress(1, tokens=4)
        assert s.progress(2, tokens=8)
        # a restarted non-resume re-decode replays chunks 1..2: silent
        assert not s.progress(1, tokens=4)
        assert not s.progress(2, tokens=8)
        assert s.progress(3, tokens=12)
        events, _ = s.next_events(0, timeout=0.0)
        assert [d["chunk"] for _s, t, d in events if t == "progress"] == [1, 2, 3]

    def test_preview_cadence_and_dedup(self):
        s = RequestStream(key="k")
        assert not s.preview_due(0, 2)  # never before chunk 1
        assert not s.preview_due(1, 2)
        assert s.preview_due(2, 2)
        assert s.preview(2, rows=[0])
        assert not s.preview_due(2, 2)  # already sent for this boundary
        assert not s.preview(2, rows=[0])
        assert not s.preview_due(3, 2)
        assert s.preview_due(4, 2)
        assert not s.preview_due(4, 0)  # 0 disables previews entirely
        assert s.previews_sent == 1

    def test_terminal_wins_once_and_seals_the_stream(self):
        s = RequestStream(key="k")
        assert s.finish("result", tokens=[[1]])
        assert not s.finish("error", status=500)  # loser of the race
        assert not s.emit("progress", chunk=9)
        assert s.finished
        events, drained = s.next_events(0, timeout=0.0)
        assert [t for _s, t, _d in events] == ["result"]
        assert not drained  # the terminal itself still had to be read
        events, drained = s.next_events(s.end_seq(), timeout=0.0)
        assert events == [] and drained

    def test_ring_is_bounded_with_absolute_seqs(self):
        s = RequestStream(key="k", max_events=8)  # 8 is the floor
        for c in range(1, 21):
            s.progress(c)
        events, _ = s.next_events(0, timeout=0.0)
        # early events fell off; sequence numbers stay absolute
        assert [seq for seq, _t, _d in events] == list(range(12, 20))
        assert [d["chunk"] for _s, _t, d in events] == list(range(13, 21))
        assert s.detail()["dropped"] == 12

    def test_attach_generations_supersede_and_orphan(self):
        s = RequestStream(key="k")
        g1 = s.attach(mark_reattach=False)
        assert s.current(g1) and s.reattaches == 0
        g2 = s.attach()  # re-attach: g1's reader must stand down
        assert s.reattaches == 1
        assert not s.current(g1) and s.current(g2)
        # a superseded reader's disconnect must NOT cancel the request
        assert not s.orphan(g1)
        assert s.orphan(g2) and s.orphaned
        # a fresh attach clears the orphan flag (client reconnected)
        g3 = s.attach()
        assert not s.orphaned and s.current(g3)


class TestStreamRegistry:
    def test_register_reattach_discard_and_gauge(self):
        seen = []
        reg = StreamRegistry(max_streams=4, gauge=seen.append)
        s = RequestStream(key="req-1")
        assert reg.register(s)
        assert seen[-1] == 1
        assert reg.get("req-1") is s
        assert reg.reattach("req-1") is s
        s.finish("result")
        assert reg.reattach("req-1") is None  # finished: nothing to join
        reg.discard(s)
        assert reg.get("req-1") is None and seen[-1] == 0

    def test_full_of_live_streams_rejects(self):
        reg = StreamRegistry(max_streams=2)
        a, b = RequestStream(key="a"), RequestStream(key="b")
        assert reg.register(a) and reg.register(b)
        assert not reg.register(RequestStream(key="c"))
        # a finished stream is evictable headroom
        a.finish("result")
        c = RequestStream(key="c")
        assert reg.register(c)
        assert reg.get("a") is None and reg.get("c") is c
        assert reg.active() == 2

    def test_detail_shape(self):
        reg = StreamRegistry(max_streams=2)
        s = RequestStream(key="a")
        reg.register(s)
        s.progress(1)
        d = reg.detail()
        assert d["active"] == 1
        assert d["streams"][0]["key"] == "a"


# --------------------------------------------------- HTTP SSE end to end


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


def _open_stream(port, body, headers=None, timeout=60):
    """POST stream=true; returns (conn, resp) with the SSE head checked."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(
        "POST", "/generate", body=json.dumps(dict(body, stream=True)),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    resp = conn.getresponse()
    assert resp.status == 200, resp.read()
    assert resp.getheader("Content-Type", "").startswith("text/event-stream")
    return conn, resp


def _read_events(resp, deadline_s=60, stop=None):
    """Drain SSE frames until a terminal event (or `stop(events)` says
    enough); returns the [(seq, etype, data)...] list in arrival order."""
    parser = SSEParser()
    events = []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        chunk = resp.read1(65536)
        if not chunk:
            break
        for etype, data, seq in parser.feed(chunk):
            events.append((seq, etype, data))
            if etype in TERMINAL_TYPES:
                return events
            if stop is not None and stop(events):
                return events
    return events


def _chunks(events, etype="progress"):
    return [d["chunk"] for _s, t, d in events if t == etype]


def _assert_gapless(events, last_chunk=N_CHUNKS):
    """THE streaming invariant: progress chunks are strictly increasing,
    duplicate-free, contiguous, and reach the final boundary."""
    chunks = _chunks(events)
    assert chunks == list(range(chunks[0], last_chunk + 1)), chunks
    seqs = [s for s, _t, _d in events if s is not None]
    assert seqs == sorted(set(seqs)), "event ids regressed or duplicated"


class TestStreamingHTTP:
    def test_stream_events_previews_and_bit_identity(self, toy):
        stream_log = io.StringIO()
        eng, server = _server(
            toy, preview_every=1, log=StructuredLog(stream=stream_log),
        )
        try:
            body = {"prompt": "red circle", "seed": 77, "timeout_s": 60}
            _, ref = _post(server.port, body)

            conn, resp = _open_stream(server.port, body)
            events = _read_events(resp)
            conn.close()
            types = [t for _s, t, _d in events]
            assert types[0] == "open" and types[-1] == "result"
            assert events[0][2]["reattach"] is False
            _assert_gapless(events)
            # previews ride every boundary at preview_every=1, as PNGs
            previews = [d for _s, t, d in events if t == "preview"]
            assert len(previews) >= 1
            assert _chunks(events, "preview") == sorted(
                set(_chunks(events, "preview"))
            )
            import base64

            png = base64.b64decode(previews[0]["previews_png_b64"][0])
            assert png.startswith(b"\x89PNG")
            assert "pixels" not in previews[0]  # raw array never hits the wire
            # the streamed request IS the request: terminal tokens match
            # the buffered run of the same body bit for bit
            result = events[-1][2]
            assert result["tokens"] == ref["tokens"]

            # satellite instruments: TTFP histogram, typed event counter,
            # live-streams gauge, /healthz detail block, log line fields
            _, text = _get(server.port, "/metrics")
            assert "dalle_serving_ttfp_seconds" in text
            assert 'dalle_serving_stream_events_total{type="preview"}' in text
            assert "dalle_serving_streams_active" in text
            _, health = _get(server.port, "/healthz")
            health = json.loads(health)
            assert health["streaming"]["preview_every"] == 1
            assert "active" in health["streaming"]
            lines = [
                json.loads(l) for l in stream_log.getvalue().splitlines()
            ]
            done = [
                l for l in lines
                if l.get("event") == "request" and l.get("streamed")
            ]
            assert done and done[-1]["outcome"] == "ok"
            assert done[-1]["previews_sent"] >= 1
            assert done[-1]["stream_reattaches"] == 0
        finally:
            server.shutdown()

    def test_stream_requires_continuous_engine(self, toy):
        from dalle_pytorch_tpu.serving.engine import GenerationEngine

        model, params, _vae, _vp = toy
        micro = GenerationEngine(
            model=model, variables=params, batch_shapes=(1, 2),
            registry=MetricsRegistry(),
        )
        micro.tokenizer = ByteTokenizer()
        server = ServingServer(micro, port=0, request_timeout_s=30).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(server.port, {"prompt": "x", "stream": True})
            assert exc.value.code == 400
            assert b"continuous" in exc.value.read()
        finally:
            server.shutdown()

    def test_client_disconnect_cancels_via_reap(self, toy):
        """Closing the SSE socket mid-decode must cancel the request: the
        writer's broken pipe marks the stream orphaned, and the batcher's
        `_reap` frees the slots at the next chunk boundary (counted by
        `dalle_serving_cancelled_total`)."""
        import socket
        import struct

        # 8 chunks (chunk_tokens=2) so the cancel lands with decode work
        # still outstanding — the reap must save real chunks, not fire
        # after the request already finished
        eng = _engine(toy, preview=False, chunk_tokens=2)
        server = ServingServer(
            eng, port=0, request_timeout_s=60, preview_every=0,
        ).start()
        hold = threading.Event()
        try:
            eng.faults = FaultInjector().stall_nth(
                "chunk", 2, seconds=30.0, until=hold
            )
            conn, resp = _open_stream(
                server.port,
                {"prompt": "goes away", "seed": 5, "timeout_s": 60},
            )
            # read up to the first progress event so the decode is
            # genuinely mid-flight, then vanish — SO_LINGER 0 turns the
            # close into an RST, so the server's next event write fails
            # immediately instead of after a buffered grace write
            events = _read_events(
                resp, stop=lambda ev: bool(_chunks(ev)),
            )
            assert _chunks(events) == [1]
            # Connection: close detached conn.sock; the live socket is
            # under the response's buffered reader
            resp.fp.raw._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
            resp.close()
            conn.close()
            hold.set()
            cancelled = server.registry.get("dalle_serving_cancelled_total")
            deadline = time.monotonic() + 30
            while cancelled.value < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert cancelled.value >= 1, "disconnect never cancelled the decode"
            deadline = time.monotonic() + 10
            while server.batcher.inflight_rows and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.batcher.inflight_rows == 0, "slots squatted after reap"
        finally:
            hold.set()
            server.shutdown()

    def test_reattach_same_key_replays_and_supersedes(self, toy):
        """A second connection with the same request key joins the LIVE
        decode: full replay from its cursor, the first reader stands down
        without cancelling, and the joined stream still ends gapless with
        the terminal result."""
        eng, server = _server(toy, preview_every=0)
        try:
            hold = threading.Event()
            eng.faults = FaultInjector().stall_nth(
                "chunk", 2, seconds=30.0, until=hold
            )
            body = {"prompt": "hand me over", "seed": 9, "timeout_s": 60}
            # the fleet join identity rides the router's request-key
            # header, exactly as a re-dispatched attempt would carry it
            key_hdr = {"x-dalle-request-key": "reattach-me"}
            conn1, resp1 = _open_stream(server.port, body, headers=key_hdr)
            events1 = _read_events(resp1, stop=lambda ev: bool(_chunks(ev)))
            assert _chunks(events1) == [1]

            conn2, resp2 = _open_stream(server.port, body, headers=key_hdr)
            hold.set()
            events2 = _read_events(resp2)
            conn1.close()
            conn2.close()
            assert events2[0][1] == "open"
            assert events2[0][2]["reattach"] is True
            _assert_gapless(events2)  # replay includes chunk 1: no gap
            assert events2[-1][1] == "result"
            # the decode ran once: re-attach joined it, not re-submitted
            assert server.streams.total_reattached >= 1
        finally:
            hold.set()
            server.shutdown()


# ------------------------------------------- compile discipline (previews)


class TestPreviewCompileDiscipline:
    def test_preview_program_is_opt_in_on_the_ladder(self, toy):
        assert "preview" in _engine(toy, preview=True).program_ladder()
        assert "preview" not in _engine(toy, preview=False).program_ladder()

    def test_warm_streaming_cycle_compiles_nothing(self, toy):
        """Warmup compiles the preview fill-decode alongside the decode
        ladder; a warm admit -> chunk -> snapshot+preview -> harvest ->
        release cycle must hit only the compile cache."""
        from dalle_pytorch_tpu.utils import assert_no_recompiles

        eng = _engine(toy)
        eng.warmup()
        ids = np.zeros(TEXT_SEQ, np.int32)
        ids[:3] = (5, 6, 7)
        with assert_no_recompiles() as tally:
            eng.prefill_slot(0, SampleSpec(ids, seed=3))
            pos, act = eng.step_chunk()
            rows = eng.snapshot_rows([0])
            pix = eng.preview_pixels(
                np.asarray(rows, np.int32),
                np.asarray([int(pos[0])], np.int32),
            )
            for _ in range(N_CHUNKS):
                pos, act = eng.step_chunk()
            eng.harvest([0])
            eng.release([0])
        assert tally.count == 0
        assert pix is not None and pix.shape[-1] == 3
        assert float(pix.min()) >= 0.0 and float(pix.max()) <= 1.0

    def test_warm_batcher_stream_cycle_compiles_nothing(self, toy):
        """The full streaming serve cycle — batcher worker, progress +
        preview events at every boundary — pins zero compiles end to end
        (the TL011 claim for the preview program, enforced live)."""
        from dalle_pytorch_tpu.utils import assert_no_recompiles

        eng = _engine(toy)
        eng.warmup()
        batcher = ContinuousBatcher(
            eng, registry=eng.registry, preview_every=1,
        )
        try:
            ids = np.zeros(TEXT_SEQ, np.int32)
            stream = RequestStream(key="warm")
            with assert_no_recompiles() as tally:
                req = batcher.submit(
                    [SampleSpec(ids, seed=8)], timeout_s=60, stream=stream,
                )
                req.future.result(timeout=60)
            assert tally.count == 0
            assert stream.previews_sent >= 1
            events, _ = stream.next_events(0, timeout=0.0)
            assert [
                d["chunk"] for _s, t, d in events if t == "progress"
            ] == list(range(1, N_CHUNKS + 1))
        finally:
            batcher.shutdown(drain=False)


# ----------------------------------------- fleet: preempt / migrate / kill


def _submit_stream(batcher, seed, key, priority="normal"):
    ids = np.arange(TEXT_SEQ, dtype=np.int32) % 5 + 1
    stream = RequestStream(key=key)
    req = batcher.submit(
        [SampleSpec(ids, seed=seed)], timeout_s=120, priority=priority,
        stream=stream,
    )
    return req, stream


class TestStreamAcrossPreemption:
    def test_preempted_stream_stays_gapless_and_bit_identical(self, toy):
        """Flavor (a): preemption -> resume on one replica. The low
        request's stream must keep its chunk sequence gapless and
        duplicate-free across the suspend/resume, and its final tokens
        equal the undisturbed run."""
        ids = np.arange(TEXT_SEQ, dtype=np.int32) % 5 + 1
        ref_eng = _engine(toy, preview=False, max_batch=2)
        ref_b = ContinuousBatcher(ref_eng, registry=ref_eng.registry)
        try:
            ref = np.asarray(ref_b.submit(
                [SampleSpec(ids, seed=88), SampleSpec(ids, seed=89)],
                timeout_s=120,
            ).future.result(timeout=120)[0])
        finally:
            ref_b.shutdown(drain=False)

        eng = _engine(toy, preview=False, max_batch=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            # park the low request mid-decode so the high arrival finds
            # both slots occupied and must preempt
            eng.faults = FaultInjector().stall_nth("chunk", 2, seconds=1.0)
            low_stream = RequestStream(key="low")
            low = b.submit(
                [SampleSpec(ids, seed=88), SampleSpec(ids, seed=89)],
                timeout_s=120, priority="low", stream=low_stream,
            )
            deadline = time.monotonic() + 30
            while not eng.faults.fired and time.monotonic() < deadline:
                time.sleep(0.005)
            high, _ = _submit_stream(b, 99, "high", priority="high")
            high.future.result(timeout=120)
            toks, _ = low.future.result(timeout=120)
            np.testing.assert_array_equal(np.asarray(toks), ref)
            assert low.preemptions >= 1
            events, _ = low_stream.next_events(0, timeout=0.0)
            chunks = [d["chunk"] for _s, t, d in events if t == "progress"]
            assert chunks == sorted(set(chunks)), chunks
            assert chunks[-1] == N_CHUNKS
            assert all(b - a == 1 for a, b in zip(chunks, chunks[1:])), chunks
        finally:
            b.shutdown(drain=False)

    def test_dispatch_failure_restart_replays_silently(self, toy):
        """A recovered dispatch failure re-admits the request from
        scratch; the re-decoded chunks replay BELOW the stream's high
        water, so the reader sees no duplicate and no regression."""
        eng = _engine(toy, preview=False, max_batch=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            eng.faults = FaultInjector().fail_nth("chunk", 3)
            req, stream = _submit_stream(b, 44, "restarted")
            toks, _ = req.future.result(timeout=120)
            assert req.dispatch_retries == 1
            events, _ = stream.next_events(0, timeout=0.0)
            chunks = [d["chunk"] for _s, t, d in events if t == "progress"]
            assert chunks == sorted(set(chunks)), chunks
            assert chunks[-1] == N_CHUNKS
        finally:
            b.shutdown(drain=False)


def _stream_fleet(toy, n=2, preview_every=2, server_kw=None, **router_kw):
    engs, servers = [], []
    for _ in range(n):
        # resume_enabled: a drain-migrated stream should RESUME on the
        # survivor (restored prefix counted), not re-decode from zero
        eng = _engine(toy, resume_enabled=True)
        engs.append(eng)
        servers.append(ServingServer(
            eng, port=0, request_timeout_s=60, preview_every=preview_every,
            **(server_kw or {}),
        ).start())
    router = FleetRouter(
        [f"r{i}=http://127.0.0.1:{s.port}" for i, s in enumerate(servers)],
        registry=MetricsRegistry(), **router_kw,
    )
    front = RouterServer(router, port=0, probes=False).start()
    return engs, servers, router, front


def _shutdown_fleet(front, servers):
    front.shutdown()
    for s in servers:
        try:
            s.shutdown()
        except Exception:
            pass


class TestStreamAcrossFleet:
    def test_drain_migrate_splices_one_continuous_stream(self, toy):
        """Flavor (b): drain?migrate=1 mid-stream. The holder 409s with a
        checkpoint; the router re-dispatches the resume to the survivor
        and SPLICES its event stream onto the same client connection —
        exactly one open, gapless duplicate-free chunks across the seam,
        bit-identical terminal tokens."""
        engs, servers, router, front = _stream_fleet(toy)
        try:
            body = {"prompt": "drain me", "seed": 901, "timeout_s": 60}
            _, ref = _post(front.port, body)

            # a timed stall (the proven drain-under-stall pattern from the
            # migration tests): the drain below is issued WHILE the holder
            # is parked inside chunk dispatch 2, and the export happens at
            # the boundary the stall releases into
            for e in engs:
                e.faults = FaultInjector().stall_nth(
                    "chunk", 2, seconds=4.0
                )
            out = {}

            def client():
                conn, resp = _open_stream(front.port, body, timeout=90)
                out["events"] = _read_events(resp, deadline_s=90)
                conn.close()

            t = threading.Thread(target=client)
            t.start()
            deadline = time.monotonic() + 30
            while not any(e.faults.fired for e in engs) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            holder = 0 if engs[0].faults.fired else 1
            engs[1 - holder].faults = None
            detail = router.drain(f"r{holder}", wait_s=30.0, migrate=True)
            assert detail["mode"] == "drained"
            t.join(timeout=90)

            events = out["events"]
            assert [t_ for _s, t_, _d in events].count("open") == 1
            assert events[-1][1] == "result"
            _assert_gapless(events)
            assert events[-1][2]["tokens"] == ref["tokens"]
            migs = {
                label: int(c.value)
                for label, c in router.registry.get(
                    "dalle_router_migrations_total"
                ).items()
            }
            assert migs.get("drain", 0) >= 1
            # the survivor resumed rather than re-decoding from scratch
            assert int(engs[1 - holder].registry.get(
                "dalle_serving_resumed_tokens_total"
            ).value) > 0
        finally:
            _shutdown_fleet(front, servers)

    def test_hard_failure_failover_stream_stays_gapless(self, toy):
        """Flavor (c): the serving replica hard-fails the request
        mid-stream (its retry budget exhausted -> terminal 5xx). The
        router must NOT forward the replica's error: it fails over, the
        survivor re-decodes from scratch, the replayed chunks are
        suppressed by the splice's high water, and the client sees one
        gapless stream with bit-identical tokens."""
        engs, servers, router, front = _stream_fleet(
            toy, preview_every=0,
            # two consecutive incidents would normally quarantine (422);
            # this test wants the terminal-5xx failover seam instead
            server_kw={"quarantine_after": 5},
        )
        try:
            body = {"prompt": "kill me", "seed": 907, "timeout_s": 60}
            _, ref = _post(front.port, body)

            # chunk dispatch 2 AND the recovery retry's first chunk both
            # fail on whichever replica takes the stream: the batcher's
            # one bounded retry dies too, so the request errors
            # terminally on that replica
            for e in engs:
                e.faults = FaultInjector().fail_nth("chunk", 2).fail_nth(
                    "chunk", 3
                )
            out = {}

            def client():
                conn, resp = _open_stream(front.port, body, timeout=90)
                out["events"] = _read_events(resp, deadline_s=90)
                conn.close()

            t = threading.Thread(target=client)
            t.start()
            deadline = time.monotonic() + 30
            while not any(e.faults.fired for e in engs) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            holder = 0 if engs[0].faults.fired else 1
            engs[1 - holder].faults = None
            t.join(timeout=90)

            events = out["events"]
            assert [t_ for _s, t_, _d in events].count("open") == 1
            assert events[-1][1] == "result", events[-1]
            _assert_gapless(events)
            assert events[-1][2]["tokens"] == ref["tokens"]
            fails = {
                label: int(c.value)
                for label, c in router.registry.get(
                    "dalle_router_failovers_total"
                ).items()
            }
            assert sum(fails.values()) >= 1, fails
        finally:
            _shutdown_fleet(front, servers)

    def test_replica_dead_before_dispatch_streams_from_survivor(self, toy):
        """Corpse flavor: ECONNREFUSED on the streaming dispatch is a
        clean failover — the client still gets one full gapless stream."""
        engs, servers, router, front = _stream_fleet(toy, preview_every=0)
        try:
            body = {"prompt": "corpse", "seed": 17, "timeout_s": 60}
            _, ref = _post(front.port, body)
            victim = min(
                range(2), key=lambda i: router.replicas[i].requests
            )
            servers[victim].shutdown(drain=False)
            conn, resp = _open_stream(front.port, body, timeout=90)
            events = _read_events(resp, deadline_s=90)
            conn.close()
            assert events[-1][1] == "result"
            _assert_gapless(events)
            assert events[-1][2]["tokens"] == ref["tokens"]
        finally:
            _shutdown_fleet(front, servers)
