"""Serving-layer unit tests: metrics registry and micro-batcher.

The batcher is driven by a FakeEngine (same `.generate`/`.max_batch`
surface as `GenerationEngine`) so every queueing policy — deadline flush,
max-batch flush, queue-full rejection, per-request timeout, cancellation,
engine fail-fast, graceful drain — is pinned without compiling a model.
"""

import threading
import time

import numpy as np
import pytest

from dalle_pytorch_tpu.serving.batcher import (
    MicroBatcher,
    QueueFullError,
    RequestCancelled,
    RequestTimeout,
    ShuttingDownError,
)
from dalle_pytorch_tpu.serving.engine import SampleSpec
from dalle_pytorch_tpu.training.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


# --------------------------------------------------------------- registry


class TestMetricsRegistry:
    def test_counter_and_gauge_render(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "requests")
        g = reg.gauge("depth", "queue depth")
        c.inc()
        c.inc(2)
        g.set(7)
        g.dec(3)
        out = reg.render()
        assert "# TYPE reqs_total counter" in out
        assert "reqs_total 3" in out
        assert "# TYPE depth gauge" in out
        assert "depth 4" in out

    def test_counter_monotonic(self):
        with pytest.raises(AssertionError):
            Counter("c").inc(-1)

    def test_histogram_buckets_and_percentiles(self):
        h = Histogram("lat", "latency", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = h.render()
        text = "\n".join(lines)
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="1"} 3' in text
        assert 'lat_bucket{le="10"} 4' in text
        assert 'lat_bucket{le="+Inf"} 5' in text
        assert "lat_count 5" in text
        assert h.count == 5
        assert h.percentile(0.5) == pytest.approx(0.5)
        assert h.percentile(0.95) == pytest.approx(50.0)
        assert h.mean() == pytest.approx(sum((0.05, 0.5, 0.5, 5.0, 50.0)) / 5)
        # boundary values land in the bucket whose bound they equal
        h2 = Histogram("edge", buckets=(1.0,))
        h2.observe(1.0)
        assert 'edge_bucket{le="1"} 1' in "\n".join(h2.render())

    def test_get_or_create_idempotent_and_typed(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        with pytest.raises(AssertionError):
            reg.gauge("x")

    def test_empty_histogram(self):
        h = Histogram("empty")
        assert h.percentile(0.5) == 0.0
        assert h.mean() == 0.0

    def test_histogram_family_labels(self):
        """Labeled per-shape series: ONE HELP/TYPE header, child samples
        tagged with the label, labels merged into bucket annotations."""
        reg = MetricsRegistry()
        fam = reg.histogram_family(
            "occ_by_shape", "occupancy by shape", label_name="shape",
            buckets=(1.0, 4.0),
        )
        fam.labels(4).observe(3)
        fam.labels(8).observe(7)
        fam.labels(4).observe(1)
        out = reg.render()
        assert out.count("# TYPE occ_by_shape histogram") == 1
        assert 'occ_by_shape_bucket{shape="4",le="1"} 1' in out
        assert 'occ_by_shape_bucket{shape="4",le="+Inf"} 2' in out
        assert 'occ_by_shape_sum{shape="4"} 4' in out
        assert 'occ_by_shape_count{shape="8"} 1' in out
        # reservoir quantile gauges stay off labeled series
        assert "occ_by_shape_p50" not in out
        # same label -> same child instrument
        assert fam.labels(4) is fam.labels("4")


# ---------------------------------------------------------------- batcher


class FakeEngine:
    """Same surface the batcher needs from GenerationEngine."""

    def __init__(self, max_batch=4, block_event=None, fail=False):
        self.max_batch = max_batch
        self.batches = []  # list of row counts seen
        self.block_event = block_event  # worker waits here if set
        self.fail = fail

    def generate(self, specs):
        if self.block_event is not None:
            assert self.block_event.wait(10.0), "test forgot to release engine"
        if self.fail:
            raise RuntimeError("XLA fell over")
        self.batches.append(len(specs))
        tokens = np.stack(
            [np.full(4, s.seed, dtype=np.int32) for s in specs]
        )
        return tokens, None


def spec(seed=0):
    return SampleSpec(text_ids=np.zeros(8, np.int32), seed=seed)


def make_batcher(engine, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return MicroBatcher(engine, **kw)


class TestMicroBatcher:
    def test_max_batch_flush_coalesces(self):
        """Four requests submitted inside the deadline window run as ONE
        padless batch of 4 — the deadline never has to expire."""
        eng = FakeEngine(max_batch=4)
        b = make_batcher(eng, max_delay_ms=2000)
        t0 = time.monotonic()
        reqs = [b.submit([spec(i)]) for i in range(4)]
        results = [r.future.result(timeout=10) for r in reqs]
        took = time.monotonic() - t0
        assert eng.batches == [4]
        assert took < 1.5, "a full batch must flush before the deadline"
        # each request got ITS row back (seed baked into the fake tokens)
        for i, (toks, pix) in enumerate(results):
            assert toks.shape == (1, 4) and int(toks[0, 0]) == i
            assert pix is None
        occ = b.registry.get("dalle_serving_batch_occupancy_rows")
        assert occ.count == 1 and occ.sum == 4
        b.shutdown()

    def test_deadline_flush_partial_batch(self):
        eng = FakeEngine(max_batch=8)
        b = make_batcher(eng, max_delay_ms=100)
        r = b.submit([spec(7)])
        toks, _ = r.future.result(timeout=10)
        assert eng.batches == [1]
        assert int(toks[0, 0]) == 7
        b.shutdown()

    def test_multi_row_requests_stay_whole(self):
        """A num_images=3 request occupies 3 contiguous rows of one batch
        and a second request fills alongside it."""
        eng = FakeEngine(max_batch=4)
        b = make_batcher(eng, max_delay_ms=500)
        r1 = b.submit([spec(1), spec(2), spec(3)])
        r2 = b.submit([spec(9)])
        t1, _ = r1.future.result(timeout=10)
        t2, _ = r2.future.result(timeout=10)
        assert eng.batches == [4]
        assert [int(t[0]) for t in t1] == [1, 2, 3]
        assert int(t2[0, 0]) == 9
        b.shutdown()

    def test_oversized_request_rejected(self):
        b = make_batcher(FakeEngine(max_batch=4))
        with pytest.raises(QueueFullError, match="exceeds max batch"):
            b.submit([spec(i) for i in range(5)])
        b.shutdown()

    def test_queue_full_backpressure(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1, max_queue_rows=2)
        first = b.submit([spec(0)])  # grabbed by the worker, blocks in engine
        time.sleep(0.2)  # let the worker take it off the queue
        queued = [b.submit([spec(1)]), b.submit([spec(2)])]
        with pytest.raises(QueueFullError, match="queue full"):
            b.submit([spec(3)])
        rejected = b.registry.get("dalle_serving_rejected_total")
        assert rejected.value == 1
        gate.set()
        for r in [first] + queued:
            r.future.result(timeout=10)
        b.shutdown()

    def test_per_request_timeout(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1)
        first = b.submit([spec(0)])
        time.sleep(0.1)
        stale = b.submit([spec(1)], timeout_s=0.05)
        time.sleep(0.2)  # stale expires while the engine is busy
        gate.set()
        first.future.result(timeout=10)
        with pytest.raises(RequestTimeout):
            stale.future.result(timeout=10)
        assert b.registry.get("dalle_serving_timeouts_total").value == 1
        b.shutdown()

    def test_cancellation_skips_request(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1)
        first = b.submit([spec(0)])
        time.sleep(0.1)
        doomed = b.submit([spec(1)])
        doomed.cancel()
        gate.set()
        first.future.result(timeout=10)
        with pytest.raises(RequestCancelled):
            doomed.future.result(timeout=10)
        # the cancelled request never cost an engine batch
        b.shutdown()
        assert eng.batches == [1]

    def test_engine_error_fails_fast(self):
        eng = FakeEngine(max_batch=4, fail=True)
        b = make_batcher(eng, max_delay_ms=50)
        r1 = b.submit([spec(0)])
        r2 = b.submit([spec(1)])
        for r in (r1, r2):
            with pytest.raises(RuntimeError, match="XLA fell over"):
                r.future.result(timeout=10)
        assert isinstance(b.last_error, RuntimeError)
        assert b.registry.get("dalle_serving_engine_errors_total").value >= 1
        b.shutdown()

    def test_graceful_shutdown_drains(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1)
        reqs = [b.submit([spec(i)]) for i in range(3)]
        time.sleep(0.1)
        gate.set()
        b.shutdown(drain=True)  # must flush everything queued
        for i, r in enumerate(reqs):
            toks, _ = r.future.result(timeout=1)  # already resolved
            assert int(toks[0, 0]) == i
        assert sum(eng.batches) == 3
        with pytest.raises(ShuttingDownError):
            b.submit([spec(9)])

    def test_hard_shutdown_fails_pending(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1)
        first = b.submit([spec(0)])
        time.sleep(0.1)
        pending = b.submit([spec(1)])
        gate.set()
        b.shutdown(drain=False)
        first.future.result(timeout=10)  # in-flight work still completes
        with pytest.raises(ShuttingDownError):
            pending.future.result(timeout=1)

    def test_queue_depth_metric_tracks(self):
        gate = threading.Event()
        eng = FakeEngine(max_batch=1, block_event=gate)
        b = make_batcher(eng, max_delay_ms=1, max_queue_rows=8)
        b.submit([spec(0)])
        time.sleep(0.1)
        b.submit([spec(1)])
        b.submit([spec(2)])
        assert b.queue_depth_rows == 2
        depth = b.registry.get("dalle_serving_queue_depth_rows")
        assert depth.value == 2
        gate.set()
        b.shutdown(drain=True)
        assert b.registry.get("dalle_serving_queue_depth_rows").value == 0
