"""Paged KV cache + prefix caching: allocator/page-table units, the
paged-vs-slotted parity contract, prefix-hit admissions, and batcher
backpressure on block exhaustion.

The load-bearing contract extends PR 2's decode-composition invariance
across CACHE LAYOUTS: a request's tokens are bit-identical whether its
K/V lives in a per-slot lane (`ContinuousEngine`) or in pool pages behind
a page table (`PagedContinuousEngine`), because the paged read path
gathers each row's logical view and runs the IDENTICAL dense/flash
kernels (models/attention.py), and both layouts share one chunk-program
body (models/dalle.py:_make_chunk_fn). Prefix-cache hits must also be
invisible in the tokens: an admission served from cached prefill pages +
sidecar decodes the same stream a cold prefill would.

Host-side allocator logic (BlockPool / PrefixCache / PagedKVManager) is
plain numpy — those tests cost microseconds. Device tests share one
module-scoped toy model and engine pair to stay fast-tier-cheap.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher, QueueFullError
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.paging import (
    GARBAGE_PAGE,
    BlockPool,
    PagedKVManager,
    chain_hashes,
)
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP
PAGE = 4


def spec(seed, head=(5, 6, 7), temperature=1.0, top_k=0.9):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[: len(head)] = head
    return SampleSpec(ids, seed=seed, temperature=temperature, top_k=top_k)


def _drain(eng, max_chunks=32):
    for _ in range(max_chunks):
        pos, act = eng.step_chunk()
        if (pos[act] >= eng.image_seq_len).all():
            return pos, act
    raise AssertionError("decode never finished")


# ------------------------------------------------------------- block pool


class TestBlockPool:
    def test_exhaustion_returns_none(self):
        p = BlockPool(4)  # garbage + 3 usable
        assert [p.alloc() for _ in range(3)] == [1, 2, 3]
        assert p.alloc() is None  # exhausted -> caller backpressures
        assert p.n_free == 0 and p.n_allocated == 3

    def test_free_then_realloc_reuses_lowest(self):
        p = BlockPool(5)
        pages = [p.alloc() for _ in range(4)]
        p.release(pages[0])
        p.release(pages[2])
        assert p.alloc() == pages[0]  # deterministic lowest-first
        assert p.alloc() == pages[2]

    def test_refcount_share_release(self):
        p = BlockPool(3)
        pg = p.alloc()
        p.share(pg)
        p.share(pg)
        assert p.refcount(pg) == 3
        p.release(pg)
        p.release(pg)
        assert p.n_free == 1  # still held by one reference
        p.release(pg)
        assert p.n_free == 2 and p.refcount(pg) == 0

    def test_garbage_page_never_allocated(self):
        p = BlockPool(3)
        assert GARBAGE_PAGE not in {p.alloc(), p.alloc()}
        with pytest.raises(AssertionError):
            p.release(GARBAGE_PAGE)

    def test_double_free_asserts(self):
        p = BlockPool(3)
        pg = p.alloc()
        p.release(pg)
        with pytest.raises(AssertionError):
            p.release(pg)

    def test_peak_watermark(self):
        p = BlockPool(6)
        a, b = p.alloc(), p.alloc()
        p.release(a)
        p.release(b)
        p.alloc()
        assert p.peak_allocated == 2


# ----------------------------------------------------------- chain hashes


class TestChainHashes:
    def test_prefix_property(self):
        """Block j's hash is a function of ids through that block's last
        K/V-relevant position only — shared prefixes produce equal hash
        chains up to the divergence block."""
        a = np.arange(1, 17, dtype=np.int32)
        b = a.copy()
        b[9:] += 100  # diverges inside block 2 (page 4: positions 8..11)
        ha = chain_hashes(a, 4, 4)
        hb = chain_hashes(b, 4, 4)
        assert ha[:2] == hb[:2]
        assert ha[2] != hb[2] and ha[3] != hb[3]

    def test_bos_offset(self):
        """Position 0 is <bos>: block 0 covers ids [:page_size-1], so two
        prompts differing only at id page_size-1 share hash 0."""
        a = np.arange(1, 17, dtype=np.int32)
        b = a.copy()
        b[3] = 99  # id 3 first matters to block 1 (position 4)
        assert chain_hashes(a, 4, 4)[0] == chain_hashes(b, 4, 4)[0]
        assert chain_hashes(a, 4, 4)[1] != chain_hashes(b, 4, 4)[1]


# --------------------------------------------------- manager + prefix cache


def _mk(n_pages=32, n_rows=2, max_entries=8):
    # text 9 positions / page 4 -> 3 text pages (2 full + partial);
    # 25 total positions -> 7 pages per row
    return PagedKVManager(
        n_rows=n_rows, page_size=4, max_positions=25, text_positions=9,
        n_pages=n_pages, max_entries=max_entries,
    )


def _ids(*head):
    ids = np.zeros(8, np.int32)
    ids[: len(head)] = head
    return ids


class TestPagedKVManager:
    def test_admit_miss_maps_and_reserves(self):
        kv = _mk()
        pages, pdst, shared, token = kv.admit_miss(0, _ids(1), register=True)
        assert len(pages) == kv.n_text_pages == 3 and shared == 0
        assert pdst != GARBAGE_PAGE  # snapshot page for the partial block
        assert (kv.table[0, :3] == pages).all()
        assert (kv.table[0, 3:] == GARBAGE_PAGE).all()
        assert kv._debt[0] == kv.pages_per_row - 3
        kv.finish_register(token, sidecar={"row": None})
        assert len(kv.cache) == 1

    def test_ensure_allocates_decode_pages(self):
        kv = _mk()
        kv.admit_miss(0, _ids(1), register=False)
        free0 = kv.pool.n_free
        kv.ensure(0, 5)
        assert (kv.table[0, :5] != GARBAGE_PAGE).all()
        assert kv.pool.n_free == free0 - 2
        assert kv._debt[0] == kv.pages_per_row - 5

    def test_release_returns_pages_and_garbage_fills(self):
        kv = _mk()
        kv.admit_miss(0, _ids(1), register=False)
        kv.ensure(0, kv.pages_per_row)
        free_before = kv.pool.n_free
        kv.release(0)
        assert (kv.table[0] == GARBAGE_PAGE).all()
        assert kv.pool.n_free == free_before + kv.pages_per_row

    def test_exhaustion_backpressure_then_recovers(self):
        """can_admit goes False when free + reclaimable pages cannot cover
        reserved debt + the new row's worst case — and comes back after a
        release, the batcher's queue-and-wait contract."""
        kv = _mk(n_pages=1 + 8, max_entries=0)  # 8 usable, 7 per row
        assert kv.can_admit([_ids(1)])
        kv.admit_miss(0, _ids(1), register=False)
        assert not kv.can_admit([_ids(2)])  # 1 free + 0 reclaimable < 7
        kv.release(0)
        assert kv.can_admit([_ids(2)])

    def test_same_wave_shared_block_registration(self):
        """Wave-local `pending_blocks`: two DISTINCT prompts sharing
        their leading full block admit onto ONE page and both register —
        without the overlay their twin pages would content-address one
        chain hash to two pages and trip `register`'s invariant."""
        kv = _mk()
        a, b = _ids(1, 2, 3), _ids(1, 2, 3, 9)
        wave: dict = {}
        pa, _, sa, ta = kv.admit_miss(0, a, register=True, pending_blocks=wave)
        pb, _, sb, tb = kv.admit_miss(1, b, register=True, pending_blocks=wave)
        assert sa == 0 and sb == 1  # b mapped a's leading page
        assert pb[0] == pa[0] and pb[1] != pa[1]
        assert kv.pool.refcount(pa[0]) >= 2  # both rows reference it
        kv.finish_register(ta, sidecar=None)
        kv.finish_register(tb, sidecar=None)  # same hash, same page: ok
        assert len(kv.cache) == 2
        kv.release(0)
        kv.release(1)
        assert kv.cache.evict_lru()  # drops the older entry (prompt a)
        assert kv.cache.peek_full(a) is None
        # both of b's blocks stay addressable through its own entry —
        # including the page it shared with the evicted prompt a
        assert kv.cache.shared_prefix_pages(b) == [pb[0], pb[1]]

    def test_capacity_probe_does_not_bump_lru(self):
        """`row_demand`/`can_admit` run on every worker wake for queued
        requests — they must not refresh the probed prompt's recency, or
        a queued-but-unadmittable prompt pins its cache entry while
        entries for prompts actually being served get evicted."""
        kv = _mk(max_entries=2)
        for i, ids in enumerate((_ids(1), _ids(2))):
            _, _, _, t = kv.admit_miss(i, ids, register=True)
            kv.finish_register(t, sidecar=None)
            kv.release(i)
        for _ in range(3):  # a parked request's repeated capacity probes
            kv.row_demand(_ids(1))
            kv.can_admit([_ids(1)])
        _, _, _, t = kv.admit_miss(0, _ids(3), register=True)
        kv.finish_register(t, sidecar=None)  # evicts the TRUE LRU: 1
        assert kv.cache.peek_full(_ids(1)) is None
        assert kv.cache.peek_full(_ids(2)) is not None

    def test_admission_headroom_matches_union_can_admit(self):
        """The batcher's O(W) accounting — one headroom snapshot debited
        by per-head `row_demand` — must reach the same verdict as the
        union `can_admit` for every wave size."""
        kv = _mk(n_pages=1 + 15, max_entries=0)  # 15 usable, 7 per row
        waves = [
            [_ids(1)],
            [_ids(1), _ids(2)],
            [_ids(1), _ids(2), _ids(3)],  # 21 > 15: must refuse
        ]
        for texts in waves:
            incremental = kv.admission_headroom() >= sum(
                kv.row_demand(t) for t in texts
            )
            assert incremental == kv.can_admit(texts)
        assert not kv.can_admit(waves[2])

    def test_can_ever_admit_bounds_request_size(self):
        kv = _mk(n_pages=1 + 8)
        assert kv.can_ever_admit(1)
        assert not kv.can_ever_admit(2)  # 14 pages can never fit 8

    def test_prefix_reuse_and_refcounts(self):
        """A second admission of the same prompt maps the cached FULL
        blocks (refcount++) instead of allocating; only the partial CoW
        page and decode pages are new."""
        kv = _mk()
        pages, pdst, _, token = kv.admit_miss(0, _ids(1), register=True)
        kv.finish_register(token, sidecar="s")
        entry = kv.cache.lookup_full(_ids(1))
        assert entry is not None and entry.sidecar == "s"
        free0 = kv.pool.n_free
        psrc, pdst2 = kv.admit_hit(1, entry)
        assert psrc == entry.partial_page and pdst2 not in pages
        assert kv.pool.n_free == free0 - 1  # ONLY the CoW page allocated
        for pg in entry.full_pages:
            assert kv.pool.refcount(pg) == 3  # row 0 + cache + row 1
        # releasing both rows leaves the cache's own references intact
        kv.release(0)
        kv.release(1)
        for pg in entry.full_pages:
            assert kv.pool.refcount(pg) == 1

    def test_shared_prefix_blocks_across_prompts(self):
        """Two different prompts sharing the first FULL block splice the
        cached page for it (chain-hash dedup), then allocate their own."""
        kv = _mk()
        a = np.arange(1, 9, dtype=np.int32)
        b = a.copy()
        b[6:] += 50  # diverge in the LAST block only
        _, _, _, token = kv.admit_miss(0, a, register=True)
        kv.finish_register(token, sidecar=None)
        _, _, shared, _ = kv.admit_miss(1, b, register=True)
        assert shared == 1  # block 0 mapped from cache, block 1 fresh
        assert kv.table[1, 0] == kv.table[0, 0]
        assert kv.table[1, 1] != kv.table[0, 1]

    def test_lru_eviction_order(self):
        kv = _mk(max_entries=2)
        for i, ids in enumerate((_ids(1), _ids(2))):
            _, _, _, t = kv.admit_miss(i, ids, register=True)
            kv.finish_register(t, sidecar=None)
            kv.release(i)
        kv.cache.lookup_full(_ids(1))  # bump: 1 becomes most-recent
        _, _, _, t = kv.admit_miss(0, _ids(3), register=True)
        kv.finish_register(t, sidecar=None)  # evicts LRU = prompt 2
        assert kv.cache.lookup_full(_ids(2)) is None
        assert kv.cache.lookup_full(_ids(1)) is not None
        assert kv.cache.evictions == 1

    def test_eviction_reclaims_pages_for_admission(self):
        """A full pool whose headroom is all cache-only pages still
        admits: allocation evicts LRU entries on demand."""
        kv = _mk(n_pages=1 + 9, max_entries=8)  # 9 usable, 7 per row
        _, _, _, t = kv.admit_miss(0, _ids(1), register=True)
        kv.finish_register(t, sidecar=None)
        kv.release(0)  # cache retains 2 full + 1 partial page
        assert kv.pool.n_allocated == 3
        assert kv.can_admit([_ids(2)])  # 6 free + 3 reclaimable >= 7
        kv.admit_miss(1, _ids(2), register=False)
        kv.ensure(1, kv.pages_per_row)  # forces eviction of prompt 1
        assert kv.cache.lookup_full(_ids(1)) is None
        assert kv.cache.evictions == 1

    def test_nested_protect_preserves_outer_pins(self):
        """`protect` returns only NEWLY pinned keys: the batcher pins a
        whole multi-split wave's hit entries, then each `prefill_slots`
        split pins (and in its finally unpins) its own — the inner unpin
        must not strip the outer wave guard, or an earlier split's
        eviction cascade could demote a later split's budgeted hit."""
        kv = _mk(max_entries=8)
        for i, ids in enumerate((_ids(1), _ids(2))):
            _, _, _, t = kv.admit_miss(i, ids, register=True)
            kv.finish_register(t, sidecar=None)
            kv.release(i)
        e1 = kv.cache.peek_full(_ids(1))
        outer = kv.cache.protect([e1.key])  # batcher's whole-wave pin
        assert outer == {e1.key}
        inner = kv.cache.protect([e1.key])  # split re-pins the same key
        assert inner == set()
        kv.cache.unprotect(inner)  # the split's finally
        assert kv.cache.evict_lru()  # skips pinned 1, takes 2
        assert kv.cache.peek_full(_ids(1)) is not None
        assert kv.cache.peek_full(_ids(2)) is None
        assert not kv.cache.evict_lru()  # only the pinned entry remains
        kv.cache.unprotect(outer)
        assert kv.cache.evict_lru()


# ------------------------------------------------------ device toy engines


@pytest.fixture(scope="module")
def toy():
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


@pytest.fixture(scope="module")
def slotted(toy):
    model, params = toy
    return ContinuousEngine(
        model=model, variables=params, max_batch=4, chunk_tokens=4,
        prefill_batch=2, registry=MetricsRegistry(),
    )


@pytest.fixture(scope="module")
def paged(toy):
    model, params = toy
    return PagedContinuousEngine(
        model=model, variables=params, max_batch=4, chunk_tokens=4,
        prefill_batch=2, page_size=PAGE, registry=MetricsRegistry(),
    )


def _tokens(eng, n):
    return jax.device_get(eng._state["img_tokens"])[:n]


class TestPagedParity:
    def test_same_wave_bit_for_bit(self, slotted, paged):
        """One admission wave through both layouts: identical tokens."""
        wave = [(0, spec(1)), (1, spec(2, (9, 9)))]
        slotted.prefill_slots(wave)
        _drain(slotted)
        ref = _tokens(slotted, 2)
        slotted.release([0, 1])
        paged.prefill_slots(wave)
        _drain(paged)
        got = _tokens(paged, 2)
        paged.release([0, 1])
        assert (ref == got).all()

    def test_same_wave_shared_leading_block(self, slotted, paged):
        """Two DISTINCT prompts sharing their first full text block in
        ONE admission wave — the prefix cache's headline workload
        (shared template/system text) — must admit, register both, and
        stay bit-for-bit with the slotted engine."""
        wave = [(0, spec(31, (21, 22, 23))), (1, spec(32, (21, 22, 23, 31)))]
        slotted.prefill_slots(wave)
        _drain(slotted)
        ref = _tokens(slotted, 2)
        slotted.release([0, 1])
        paged.prefill_slots(wave)
        _drain(paged)
        got = _tokens(paged, 2)
        paged.release([0, 1])
        assert (ref == got).all()
        e1 = paged.kv.cache.peek_full(
            np.asarray(wave[0][1].text_ids, np.int32)
        )
        e2 = paged.kv.cache.peek_full(
            np.asarray(wave[1][1].text_ids, np.int32)
        )
        assert e1 is not None and e2 is not None
        assert e1.full_pages[0] == e2.full_pages[0]  # one shared page
        assert e1.full_pages[1] != e2.full_pages[1]  # divergent block

    def test_staggered_admission_parity(self, slotted, paged):
        """Mid-flight admission puts rows at DIFFERENT lengths (different
        live page counts per row): every row still matches the slotted
        engine's staggered decode bit-for-bit."""
        a, b = spec(11, (3, 1)), spec(12, (8, 2, 6))
        for eng in (slotted, paged):
            eng.prefill_slots([(0, a)])
            eng.step_chunk()  # row 0 advances 4 tokens alone
            eng.prefill_slots([(1, b)])  # row 1 admitted mid-flight
            _drain(eng)
        ref = _tokens(slotted, 2)
        got = _tokens(paged, 2)
        slotted.release([0, 1])
        paged.release([0, 1])
        assert (ref == got).all()
        # sanity: the two rows decode different streams (the parity is not
        # vacuous equality of constants)
        assert (ref[0] != ref[1]).any()


class TestPrefixCache:
    def test_hit_serves_identical_tokens(self, paged):
        """A prefix-cache admission (zero prefill dispatches) decodes the
        SAME tokens as the cold prefill of that (prompt, seed)."""
        s = spec(77, (4, 2))
        paged.prefill_slots([(0, s)])
        assert paged.last_admission_stats["prefix_hits"] == 0
        _drain(paged)
        cold = _tokens(paged, 1)[0]
        paged.release([0])
        disp0 = paged.registry.get(
            "dalle_serving_prefill_dispatches_total"
        ).value
        paged.prefill_slots([(2, s)])  # different slot, same prompt+seed
        st = paged.last_admission_stats
        assert st["prefix_hits"] == 1 and st["dispatches"] == 0
        assert st["hit_slots"] == [2]
        assert paged.registry.get(
            "dalle_serving_prefill_dispatches_total"
        ).value == disp0  # ZERO transformer dispatches for the admission
        _drain(paged)
        hit = jax.device_get(paged._state["img_tokens"])[2]
        paged.release([2])
        assert (cold == hit).all()

    def test_snapshot_survives_hit_decode(self, paged):
        """Copy-on-write at the divergence block: a hit's decode mutates
        its PRIVATE copy, so a later hit of the same prompt still serves
        identical tokens (a shared mutable page would corrupt here)."""
        s = spec(33, (7, 7, 7))
        paged.prefill_slots([(0, s)])
        _drain(paged)
        first = _tokens(paged, 1)[0].copy()
        paged.release([0])
        for _ in range(2):  # two consecutive hit-admissions
            paged.prefill_slots([(1, s)])
            assert paged.last_admission_stats["prefix_hits"] == 1
            _drain(paged)
            again = jax.device_get(paged._state["img_tokens"])[1]
            paged.release([1])
            assert (first == again).all()

    def test_block_gauges_and_healthz_detail(self, paged):
        det = paged.kv_detail()
        assert det["layout"] == "paged" and det["page_size"] == PAGE
        assert det["blocks_active"] == paged.kv.blocks_active
        assert (
            det["blocks_active"] + det["blocks_free"] == det["blocks_total"]
        )
        assert paged.registry.get(
            "dalle_serving_blocks_active"
        ).value == paged.kv.blocks_active
        assert paged.registry.get(
            "dalle_serving_blocks_free"
        ).value == paged.kv.blocks_free
        hits = paged.registry.get("dalle_serving_prefix_cache_hits_total")
        assert hits.value == paged.kv.cache.hits > 0


class TestWarmServer:
    def test_full_cycle_zero_recompiles(self, paged):
        """After warmup, a complete admit(miss)→chunk→mid-flight admit→
        harvest→release→admit(hit) cycle compiles NOTHING."""
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        paged.warmup()  # also resets device + paging state
        with assert_no_recompiles():
            paged.prefill_slots([(0, spec(1)), (1, spec(2, (9, 9)))])
            paged.step_chunk()
            paged.prefill_slots([(2, spec(3, (4, 4)))])
            _drain(paged)
            toks = paged.harvest([0, 1, 2])
            paged.release([0, 1, 2])
            paged.prefill_slots([(3, spec(9))])  # warm prefix hit
            assert paged.last_admission_stats["prefix_hits"] == 1
            _drain(paged)
            paged.release([3])
        assert toks.shape == (3, IMG_SEQ)


# ------------------------------------------------- batcher block gating


class FakePagedEngine:
    """Block-pool surface double: the batcher's admission gate must hold
    requests while `can_admit` is False and reject at submit when
    `can_ever_admit` is False — without any device work."""

    image_seq_len = 8
    max_batch = 4
    chunk = 4

    def __init__(self, admit_ok=True, ever_ok=True):
        self.registry = MetricsRegistry()
        self.admit_ok = admit_ok
        self.ever_ok = ever_ok
        self.admit_checks = threading.Event()
        self.pos = np.zeros(self.max_batch, np.int64)
        self.active = np.zeros(self.max_batch, bool)
        self.seeds = np.zeros(self.max_batch, np.int64)

    def can_admit(self, specs):
        self.admit_checks.set()
        return self.admit_ok

    def can_ever_admit(self, specs):
        return self.ever_ok

    def prefill_slot(self, slot, sp):
        self.pos[slot] = 0
        self.active[slot] = True
        self.seeds[slot] = sp.seed

    def step_chunk(self):
        live = self.active & (self.pos < self.image_seq_len)
        self.pos[live] += self.chunk
        return self.pos.copy(), self.active.copy()

    def harvest(self, slots):
        return np.stack([
            np.full(self.image_seq_len, self.seeds[s], np.int32)
            for s in slots
        ])

    def release(self, slots):
        for s in slots:
            self.active[s] = False

    def decode_pixels(self, tokens):
        return None

    def slots_active_gauge(self, n):
        self.registry.gauge("dalle_serving_slots_active").set(n)


class FakeIncrementalEngine(FakePagedEngine):
    """Exposes the O(W) admission hooks (`admission_headroom` /
    `admission_demand`) the real paged engine publishes, so the batcher
    takes the incremental path instead of the union-`can_admit`
    fallback."""

    def __init__(self, budget=10, demand=7, **kw):
        super().__init__(**kw)
        self.budget = budget
        self.demand = demand
        self.live = 0
        self.peak_live = 0

    def admission_headroom(self):
        return self.budget - self.live * self.demand

    def admission_demand(self, specs):
        return self.demand * len(specs)

    def prefill_slot(self, slot, sp):
        super().prefill_slot(slot, sp)
        self.live += 1
        self.peak_live = max(self.peak_live, self.live)

    def release(self, slots):
        super().release(slots)
        self.live -= len(slots)


class FakePrefixEngine(FakePagedEngine):
    """Adds the paged admission-stats surface: batched `prefill_slots`
    publishing `last_admission_stats`, alternating miss then hit."""

    prefill_batch = 2

    def __init__(self, **kw):
        super().__init__(**kw)
        self.admissions = 0

    def prefill_slots(self, assignments):
        hit = self.admissions > 0  # first wave misses, later ones hit
        self.admissions += 1
        for slot, sp in assignments:
            self.prefill_slot(slot, sp)
        self.last_admission_stats = {
            "wave_rows": len(assignments),
            "prefix_hits": len(assignments) if hit else 0,
            "hit_slots": [s for s, _ in assignments] if hit else [],
            "prefix_blocks_reused": 2 * len(assignments) if hit else 0,
            "suffix_tokens_computed": 0 if hit else 9 * len(assignments),
            "dispatches": 0 if hit else 1,
        }


class FakeWaveGuardEngine(FakePrefixEngine):
    """Splits every multi-row wave (prefill_batch=1) and checks each
    split dispatch runs under a protection that covers the WHOLE wave."""

    prefill_batch = 1

    def __init__(self, **kw):
        super().__init__(**kw)
        self.protected = None
        self.guard_events = []
        self.split_wave_sizes = []

    def protect_admission_wave(self, assignments):
        self.protected = {int(sp.seed) for _, sp in assignments}
        self.guard_events.append(("protect", len(assignments)))
        return set(self.protected)

    def unprotect_admission_wave(self, keys):
        self.guard_events.append(("unprotect", len(keys)))
        self.protected = None

    def prefill_slots(self, assignments):
        assert self.protected is not None, "split dispatched unguarded"
        self.split_wave_sizes.append(len(self.protected))
        super().prefill_slots(assignments)


class TestBatcherBlockGating:
    def test_wave_guard_spans_all_splits(self):
        """A wave budgeted once but dispatched in prefill_batch-sized
        splits keeps its prefix-cache protection for EVERY split — the
        guard is taken before split 1 and dropped only after the last."""
        eng = FakeWaveGuardEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        r = b.submit([spec(1), spec(2)])  # one 2-row wave, 2 splits
        r.future.result(timeout=10)
        b.shutdown()
        assert eng.split_wave_sizes == [2, 2]  # both splits saw the wave
        assert eng.guard_events == [("protect", 2), ("unprotect", 2)]

    def test_prefill_span_and_prefix_hit_flag(self):
        """The obs contract: the prefill span carries the admission
        stats (prefix_blocks_reused / suffix_tokens_computed) and each
        request learns whether it admitted via the prefix cache."""
        from dalle_pytorch_tpu.obs.tracing import Tracer

        eng = FakePrefixEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        tracer = Tracer(enabled=True)
        reqs = []
        for i in range(2):
            t = tracer.start_trace("request")
            r = b.submit([spec(i)], trace=t)
            r.future.result(timeout=10)
            t.finish()
            reqs.append((r, t))
        b.shutdown()
        assert reqs[0][0].prefix_hit is False
        assert reqs[1][0].prefix_hit is True
        for r, t in reqs:
            (pf,) = [s for s in t.spans if s.name == "prefill"]
            assert pf.args["prefix_hit"] is r.prefix_hit
            if r.prefix_hit:
                assert pf.args["prefix_blocks_reused"] == 2
                assert pf.args["suffix_tokens_computed"] == 0
                assert pf.args["dispatches"] == 0
            else:
                assert pf.args["suffix_tokens_computed"] == 9
                assert pf.args["dispatches"] == 1

    def test_block_exhaustion_queues_until_free(self):
        """can_admit False parks the request (backpressure, not failure);
        flipping it True lets the SAME worker admit it — no deadlock."""
        eng = FakePagedEngine(admit_ok=False)
        b = ContinuousBatcher(eng, registry=eng.registry)
        r = b.submit([spec(5)])
        assert eng.admit_checks.wait(10.0)  # worker saw it and held it
        assert not r.future.done()
        eng.admit_ok = True
        with b._cond:  # poke the worker the way submit/release do
            b._cond.notify_all()
        toks, _ = r.future.result(timeout=10)
        assert int(toks[0, 0]) == 5
        b.shutdown()

    def test_incremental_joint_overrun_not_coadmitted(self):
        """Two requests that each fit alone must not be co-admitted when
        they jointly overrun the block budget — through the incremental
        headroom/demand hooks, not the union fallback. Both still finish
        (the second waits for the first's release)."""
        eng = FakeIncrementalEngine(budget=10, demand=7)
        b = ContinuousBatcher(eng, registry=eng.registry)
        with b._cond:  # hold the worker so both requests queue together
            r1 = b.submit([spec(1)])
            r2 = b.submit([spec(2)])
        for r, want in ((r1, 1), (r2, 2)):
            toks, _ = r.future.result(timeout=10)
            assert int(toks[0, 0]) == want
        assert eng.peak_live == 1  # never both live at once
        b.shutdown()

    def test_oversized_request_rejected_at_submit(self):
        eng = FakePagedEngine(ever_ok=False)
        b = ContinuousBatcher(eng, registry=eng.registry)
        with pytest.raises(QueueFullError, match="block pool"):
            b.submit([spec(1)])
        b.shutdown()


# ----------------------------------------------------------- scan executor


@pytest.mark.slow
class TestScanExecutorParity:
    def test_paged_matches_slotted_scan(self):
        """The depth-stacked scan-executor cache pages identically (the
        page table is broadcast across the depth axis)."""
        model = DALLE(
            dim=32, depth=2, heads=2, dim_head=8,
            num_image_tokens=32, image_fmap_size=FMAP,
            num_text_tokens=64, text_seq_len=TEXT_SEQ,
            shift_tokens=True, rotary_emb=True, executor="scan",
        )
        text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
        toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
        params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
        slot = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, registry=MetricsRegistry(),
        )
        paged = PagedContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, page_size=PAGE, registry=MetricsRegistry(),
        )
        wave = [(0, spec(1)), (1, spec(2, (9, 9)))]
        slot.prefill_slots(wave)
        _drain(slot)
        ref = _tokens(slot, 2)
        paged.prefill_slots(wave)
        _drain(paged)
        assert (ref == _tokens(paged, 2)).all()
