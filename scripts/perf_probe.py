"""Perf triage probe: where does the flagship train step's time go?

Prints one JSON line per experiment. Chasing the round-3 MFU gap; results
land in BASELINE.md.

Measurement notes for the axon-tunneled TPU: `block_until_ready` does not
actually block, and each dispatch pays a large round trip. So every probe
runs its op K times INSIDE one jitted program (lax.fori_loop / lax.scan
with data dependence between iterations), makes exactly one dispatch, and
forces completion with a scalar readback. Wall time / K ≈ device time per
op, with one RTT amortized over the whole loop.

Probes:
  peak    — chained bf16 8192^3 matmuls: achievable MXU FLOP/s ceiling
  hbm     — chained elementwise pass over a 1 GiB array: achievable HBM
            read+write bandwidth (the roofline's other axis)
  attn    — one dense attention layer fwd+bwd at flagship geometry
  ff      — one GEGLU FF block fwd+bwd at flagship geometry
  logits  — logits head (18448 vocab) + CE fwd+bwd
  step    — full flagship train step (remat on), scanned K times
  step_noremat — same, remat off, microbatch 8
  fwd     — flagship forward+loss only

Usage: python scripts/perf_probe.py [probe ...]   (default: all)
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

K = int(os.environ.get("PROBE_K", "8"))


def run_probe(name, build, flops_per_iter, emit, k=K):
    """build() -> (jitted_fn, args); jitted_fn must run the op `k` times
    internally and return something reducible to a scalar."""
    import jax
    import jax.numpy as jnp

    fn, args = build()
    out = fn(*args)
    _ = float(jnp.asarray(out).ravel()[0])  # compile + warm, forced
    t0 = time.perf_counter()
    out = fn(*args)
    _ = float(jnp.asarray(out).ravel()[0])
    secs = (time.perf_counter() - t0) / k
    rec = {"probe": name, "ms_per_iter": round(secs * 1e3, 2), "k": k}
    if flops_per_iter:
        rec["tflops_per_sec"] = round(flops_per_iter / secs / 1e12, 1)
    emit(rec)


def main():
    import jax

    # the image's sitecustomize pins jax_platforms="axon,cpu"; the env var
    # alone cannot override it — must update config after import
    if os.environ.get("PROBE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])
    import jax.numpy as jnp
    from jax import lax

    only = set(sys.argv[1:]) or None
    dev = jax.devices()[0].device_kind

    def emit(rec):
        rec["device"] = dev
        print(json.dumps(rec), flush=True)

    def want(name):
        return only is None or name in only

    # flagship geometry by default; PROBE_DIM/PROBE_DEPTH/PROBE_FMAP shrink
    # it for CPU smoke runs of the probe script itself
    dim = int(os.environ.get("PROBE_DIM", "1024"))
    depth = int(os.environ.get("PROBE_DEPTH", "12"))
    heads, dim_head = 16, dim // 16
    text_seq = int(os.environ.get("PROBE_TEXT_SEQ", "256"))
    fmap = int(os.environ.get("PROBE_FMAP", "32"))
    image_seq = fmap * fmap
    seq = text_seq + image_seq
    batch = int(os.environ.get("PROBE_BATCH", "16"))
    inner = heads * dim_head

    if want("peak"):
        n = 8192

        def build():
            a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
            b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)

            @jax.jit
            def loop(a, b):
                def body(_, x):
                    y = x @ b
                    return y * lax.rsqrt(jnp.float32(n)).astype(y.dtype)

                return lax.fori_loop(0, K, body, a)

            return loop, (a, b)

        run_probe("peak_matmul_bf16_8192", build, 2 * n**3, emit)

    if want("hbm"):
        # streaming read+write of a 1 GiB bf16 buffer; XLA can't fuse the
        # iterations away because each depends on the previous value.
        # Reported as GB/s = 2 * size / t (one read + one write per pass).
        elems = int(os.environ.get("PROBE_HBM_ELEMS", str(512 * 1024 * 1024)))

        def build():
            x = jnp.ones((elems,), jnp.bfloat16)

            @jax.jit
            def loop(x):
                def body(i, x):
                    # bf16-representable, sign-alternating perturbation: the
                    # value genuinely changes every iteration, so no legal
                    # simplifier pass can elide the dependence chain.
                    delta = jnp.where(i % 2 == 0, jnp.bfloat16(0.25),
                                      jnp.bfloat16(-0.25))
                    return x + delta

                return lax.fori_loop(0, K, body, x)

            return loop, (x,)

        def emit_bw(rec):
            secs = rec["ms_per_iter"] / 1e3
            rec = dict(rec)
            rec["gbytes_per_sec"] = round(2 * elems * 2 / secs / 1e9, 1)
            rec["buffer_gib"] = round(elems * 2 / 2**30, 2)
            emit(rec)

        run_probe("hbm_stream_bw", build, None, emit_bw)

    def grad_loop_probe(name, module, x_shape, flops):
        """K chained fwd+bwd of `module` inside one jit: x <- x - 1e-3*dx."""

        def build():
            x = jax.random.normal(jax.random.PRNGKey(0), x_shape, jnp.bfloat16)
            params = module.init(jax.random.PRNGKey(1), x)

            def loss(p, x):
                out = module.apply(p, x)
                if isinstance(out, tuple):  # Attention returns (out, cache)
                    out = out[0]
                return out.astype(jnp.float32).mean()

            g = jax.grad(loss, argnums=1)

            @jax.jit
            def loop(params, x):
                def body(_, x):
                    return x - 1e-3 * g(params, x).astype(x.dtype)

                return lax.fori_loop(0, K, body, x)

            return loop, (params, x)

        run_probe(name, build, flops, emit)

    if want("attn"):
        from dalle_pytorch_tpu.models.attention import Attention

        attn = Attention(
            dim=dim, heads=heads, dim_head=dim_head, causal=True, seq_len=seq,
            dtype=jnp.bfloat16,
        )
        fl = 3 * batch * (
            2 * seq * dim * 3 * inner
            + 2 * seq * seq * inner * 2
            + 2 * seq * inner * dim
        )
        grad_loop_probe("attn_layer_grad", attn, (batch, seq, dim), fl)

    if want("ff"):
        from dalle_pytorch_tpu.models.transformer import FeedForward

        ff = FeedForward(dim=dim, mult=4, dtype=jnp.bfloat16)
        fl = 3 * batch * (2 * seq * dim * 4 * dim * 2 + 2 * seq * dim * 4 * dim)
        grad_loop_probe("ff_block_grad", ff, (batch, seq, dim), fl)

    if want("logits"):
        total_tokens = 10000 + text_seq + 8192

        def build():
            w = (
                jax.random.normal(
                    jax.random.PRNGKey(0), (dim, total_tokens), jnp.bfloat16
                )
                * 0.02
            )
            h = jax.random.normal(
                jax.random.PRNGKey(1), (batch, seq, dim), jnp.bfloat16
            )
            labels = jnp.zeros((batch, seq), jnp.int32)

            def loss(w, h):
                logits = (h @ w).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()

            g = jax.grad(loss)

            @jax.jit
            def loop(w, h):
                def body(_, w):
                    return w - 1e-3 * g(w, h).astype(w.dtype)

                return lax.fori_loop(0, K, body, w)

            return loop, (w, h)

        run_probe(
            "logits_head_grad", build, 3 * 2 * batch * seq * dim * total_tokens, emit
        )

    def flagship_flops(b):
        from dalle_pytorch_tpu.utils.flops import transformer_train_flops

        return transformer_train_flops(
            dim, depth, heads, dim_head, seq, vocab=10000 + text_seq + 8192
        ) * b

    if want("step") or want("step_noremat") or want("fwd"):
        from dalle_pytorch_tpu.models.dalle import DALLE
        from dalle_pytorch_tpu.training import (
            TrainState,
            make_optimizer,
            make_dalle_train_step,
        )

        def make_model(remat, attn_impl="auto"):
            return DALLE(
                dim=dim, depth=depth, heads=heads, dim_head=dim_head,
                num_image_tokens=8192, image_fmap_size=fmap,
                num_text_tokens=10000, text_seq_len=text_seq,
                shift_tokens=True, rotary_emb=True, attn_impl=attn_impl,
                reversible=remat, reversible_impl="remat",
                dtype=jnp.bfloat16,
            )

        attn_impl = os.environ.get("PROBE_ATTN", "auto")

        for name, remat, b in (
            ("step", True, batch),
            ("step_noremat", False, int(os.environ.get("PROBE_NOREMAT_BATCH", "8"))),
        ):
            if not want(name):
                continue

            def build(remat=remat, b=b):
                model = make_model(remat, attn_impl)
                text = jnp.ones((b, text_seq), jnp.int32)
                tokens = jnp.zeros((b, image_seq), jnp.int32)
                params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)[
                    "params"
                ]
                state = TrainState.create(
                    apply_fn=model.apply, params=params,
                    tx=make_optimizer(3e-4, clip_grad_norm=0.5),
                )
                step = make_dalle_train_step(model)
                batch_dict = {"text": text, "image_tokens": tokens}

                @jax.jit
                def loop(state, batch_dict, rng):
                    def body(carry, r):
                        st, _ = carry
                        st, metrics = step(st, batch_dict, r)
                        return (st, metrics["loss"]), None

                    (st, loss), _ = lax.scan(
                        body,
                        (state, jnp.float32(0)),
                        jax.random.split(rng, K),
                    )
                    return loss

                return loop, (state, batch_dict, jax.random.PRNGKey(1))

            run_probe(f"{name}_b{b}_{attn_impl}", build, flagship_flops(b), emit)

        if want("fwd"):

            def build():
                model = make_model(False, attn_impl)
                text = jnp.ones((batch, text_seq), jnp.int32)
                tokens = jnp.zeros((batch, image_seq), jnp.int32)
                variables = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)

                @jax.jit
                def loop(variables, text, tokens):
                    def body(_, acc):
                        # tie the inputs to the carry (always +0, but data-
                        # dependent) so loop-invariant code motion can't
                        # hoist the forward out of the loop
                        t = text + (acc == jnp.inf).astype(jnp.int32)
                        loss, _ = model.apply(
                            variables, t, tokens, return_loss=True,
                            deterministic=True,
                        )
                        return acc + loss

                    return lax.fori_loop(0, K, body, jnp.float32(0))

                return loop, (variables, text, tokens)

            run_probe(f"fwd_b{batch}_{attn_impl}", build, flagship_flops(batch) / 3, emit)


if __name__ == "__main__":
    main()
