"""Compile-time A/B: unrolled vs scan executor at flagship geometry.

The scan executor exists to shrink the compiled program (~depth× fewer
layer bodies in the HLO). This measures trace+lower and XLA-compile wall
time for the full flagship train step on the CPU backend (compile cost is
a property of program structure, not the executing backend) plus the HLO
text size as a proxy for what the TPU tunnel's remote-compile endpoint
has to swallow — the relay has died mid-compile on the unrolled flagship
program twice (BASELINE.md).

Run: python scripts/compile_time_ab.py          (one JSON line per row)
Env: AB_BATCH (default 4), AB_DEPTH (12), AB_EXECUTORS (unrolled,scan)
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.training import (
        TrainState, make_optimizer, make_dalle_train_step,
    )

    batch = int(os.environ.get("AB_BATCH", "4"))
    depth = int(os.environ.get("AB_DEPTH", "12"))
    execs = os.environ.get("AB_EXECUTORS", "unrolled,scan").split(",")

    for executor in execs:
        model = DALLE(
            dim=1024, depth=depth, heads=16, dim_head=64,
            num_image_tokens=8192, image_fmap_size=32,
            num_text_tokens=10000, text_seq_len=256,
            shift_tokens=True, rotary_emb=True, attn_impl="dense",
            reversible=True, reversible_impl="remat",
            remat_policy="dots_with_no_batch_dims_saveable",
            fused_ce=True, executor=executor, dtype=jnp.bfloat16,
        )
        text = jnp.ones((batch, 256), jnp.int32)
        tokens = jnp.zeros((batch, 1024), jnp.int32)
        t0 = time.perf_counter()
        params = jax.eval_shape(
            lambda: jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)
        )["params"]
        params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
        init_s = time.perf_counter() - t0

        state = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=make_optimizer(3e-4, clip_grad_norm=0.5),
        )
        step = jax.jit(make_dalle_train_step(model), donate_argnums=0)
        batch_dict = {"text": text, "image_tokens": tokens}
        rng = jax.random.PRNGKey(1)

        t0 = time.perf_counter()
        lowered = step.lower(state, batch_dict, rng)
        lower_s = time.perf_counter() - t0
        hlo_chars = len(lowered.as_text())
        t0 = time.perf_counter()
        lowered.compile()
        compile_s = time.perf_counter() - t0
        print(json.dumps({
            "probe": "compile_ab", "executor": executor, "depth": depth,
            "batch": batch,
            "trace_lower_s": round(lower_s, 1),
            "xla_compile_s": round(compile_s, 1),
            "hlo_mb": round(hlo_chars / 1e6, 1),
            "param_init_s": round(init_s, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
