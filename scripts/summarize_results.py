"""Summarize TPU experiment artifacts into a markdown table.

Reads TPU_RESULTS.jsonl (watchdog matrix) and/or EXTRA_RESULTS.jsonl
(bench.py opportunistic extras) and prints:

  * a bench table (profile/config -> img-tok/s/chip, MFU, samples/s),
  * the generate north star (p50, tokens/s),
  * the dense-vs-flash/lib_flash/splash A/B with a data-driven
    recommendation for AUTO_FLASH_MIN_SEQ (models/attention.py),
  * peak/HBM probe numbers for the roofline.

Usage: python scripts/summarize_results.py [files...]
Default inputs: TPU_RESULTS.jsonl EXTRA_RESULTS.jsonl (repo root).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def load(paths):
    recs = []
    for p in paths:
        p = Path(p)
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return recs


def flat_results(recs):
    """Matrix rows are {experiment, result}; extras are the same; bench
    child lines may also appear bare. Yield (experiment, result-dict)."""
    for r in recs:
        if "experiment" in r:
            res = r.get("result")
            if isinstance(res, dict):
                yield r["experiment"], res
        elif "metric" in r or "probe" in r:
            yield r.get("metric") or r.get("probe"), r


def main():
    paths = sys.argv[1:] or [ROOT / "TPU_RESULTS.jsonl", ROOT / "EXTRA_RESULTS.jsonl"]
    rows = list(flat_results(load(paths)))
    if not rows:
        print("no results found in", [str(p) for p in paths])
        return

    bench, probes, ab, gen = [], [], [], []
    for name, r in rows:
        if r.get("metric", "").startswith("dalle_train"):
            bench.append((name, r))
        elif r.get("metric", "").startswith("generate"):
            gen.append((name, r))
        elif r.get("probe") in ("ab", "block_sweep", "lib_flash", "splash"):
            ab.append(r)
        elif r.get("probe"):
            probes.append(r)

    if bench:
        print("## Training bench\n")
        print("| run | config | img-tok/s/chip | MFU | samples/s | ok |")
        print("|---|---|---|---|---|---|")
        for name, r in bench:
            print(
                f"| {name} | {r.get('profile') or r.get('config', '')} | "
                f"{r.get('value')} | {r.get('mfu')} | "
                f"{r.get('samples_per_sec')} | "
                f"{r.get('ok')}{' (CPU)' if r.get('fallback') else ''} |"
            )
        best = max(
            (r for _, r in bench if r.get("ok") and not r.get("fallback")),
            key=lambda r: r.get("value") or 0,
            default=None,
        )
        if best:
            print(
                f"\nBest: {best['value']} img-tok/s/chip "
                f"(MFU {best.get('mfu')}) @ {best.get('config')}"
            )

    # dispatch-overhead split: pair single-dispatch and steps-S rows whose
    # configs differ ONLY in '-stepsS'. With per-step walls t1 and tS,
    #   RTT  = (t1 - tS) * S/(S-1)        (fixed per-dispatch cost)
    #   tdev = tS - RTT/S = (S*tS - t1)/(S-1)   (pure device step time)
    # probe_step (K steps inside ONE jit) is the zero-dispatch
    # cross-check for tdev.
    by_cfg = {}
    for name, r in bench:
        cfg = r.get("config")
        if not (cfg and r.get("ok") and not r.get("fallback")):
            continue
        if not r.get("samples_per_sec"):
            continue
        m = re.search(r"gbs(\d+)", cfg)
        s = re.search(r"-steps(\d+)", cfg)
        if not m:
            continue
        steps = int(s.group(1)) if s else 1
        key = re.sub(r"-steps\d+", "", cfg)
        t_step = int(m.group(1)) / r["samples_per_sec"]
        prev = by_cfg.setdefault(key, {})
        if steps not in prev or t_step < prev[steps]:
            prev[steps] = t_step
    splits = []
    for key, walls in by_cfg.items():
        if 1 not in walls:
            continue
        for s, ts in walls.items():
            if s > 1:
                # rtt <= 0 is itself the answer ("dispatch is NOT the
                # bottleneck") — report it, don't drop the pair
                rtt = (walls[1] - ts) * s / (s - 1)
                tdev = (s * ts - walls[1]) / (s - 1)
                splits.append((key, s, walls[1], ts, rtt, tdev))
    if splits:
        print("\n## Dispatch-overhead split\n")
        print("| config | S | t1 s/step | tS s/step | RTT/dispatch | device s/step |")
        print("|---|---|---|---|---|---|")
        for key, s, t1, ts, rtt, tdev in splits:
            note = " (no positive overhead)" if rtt <= 0 else ""
            print(
                f"| {key} | {s} | {t1:.3f} | {ts:.3f} | {rtt:.3f}{note} | "
                f"{tdev:.3f} |"
            )

    if gen:
        print("\n## Generate north star\n")
        for name, r in gen:
            tag = " (CPU)" if r.get("fallback") else ""
            print(
                f"- {name}: p50 {r.get('value')}s / batch {r.get('batch')}"
                f" = {r.get('tokens_per_sec')} tok/s{tag}  [{r.get('config')}]"
            )

    if ab:
        print("\n## Attention kernel A/B (fwd+bwd ms)\n")
        print("| seq | dense | flash | lib_flash | splash | bq:bk sweep |")
        print("|---|---|---|---|---|---|")
        by_seq = {}

        def keep_min(s, key, val):
            # duplicate rows across watchdog re-runs: best (min ms) wins,
            # and a null from a truncated run never clobbers a real timing
            if val is not None and (s.get(key) is None or val < s[key]):
                s[key] = val

        for r in ab:
            s = by_seq.setdefault(r.get("seq"), {})
            if r.get("probe") == "ab":
                keep_min(s, "dense", r.get("dense_ms"))
                keep_min(s, "flash", r.get("flash_ms"))
            elif r.get("probe") == "lib_flash":
                keep_min(s, "lib_flash", r.get("lib_flash_ms"))
            elif r.get("probe") == "splash":
                keep_min(s, "splash", r.get("splash_ms"))
            elif r.get("probe") == "block_sweep" and r.get("flash_ms"):
                s.setdefault("sweep", []).append(
                    (r["flash_ms"], f"{r['bq']}:{r['bk']}")
                )
        for seq in sorted(k for k in by_seq if k):
            s = by_seq[seq]
            sweep = ""
            if s.get("sweep"):
                ms, label = min(s["sweep"])
                sweep = f"best {label} @ {ms}ms"
            print(
                f"| {seq} | {s.get('dense')} | {s.get('flash')} | "
                f"{s.get('lib_flash')} | {s.get('splash')} | {sweep} |"
            )
        # AUTO_FLASH_MIN_SEQ recommendation: smallest seq where any flash
        # variant beats dense
        candidates = sorted(
            seq for seq, s in by_seq.items()
            if seq and s.get("dense") and any(
                s.get(k) and s[k] < s["dense"]
                for k in ("flash", "lib_flash", "splash")
            )
        )
        if candidates:
            print(
                f"\nRecommendation: AUTO_FLASH_MIN_SEQ = {candidates[0]} "
                "(smallest measured seq where a flash variant beats dense; "
                "models/attention.py)"
            )

    if probes:
        print("\n## Probes\n")
        for r in probes:
            extra = {
                k: v for k, v in r.items()
                if k not in ("probe", "device", "k") and v is not None
            }
            print(f"- {r['probe']}: {json.dumps(extra)}")

    # pp trunk cost check (scripts/pp_bench.py)
    pp = [r for _, r in rows if r.get("metric") == "pp_trunk_step_overhead"]
    if pp:
        print("\n## Pipeline-parallel trunk cost\n")
        for r in pp:
            tag = " (CPU)" if r.get("fallback") else ""
            print(
                f"- pp={r.get('pp')} n_micro={r.get('n_micro')}: "
                f"{r.get('value')}x plain ({r.get('pp_s')}s vs "
                f"{r.get('plain_s')}s){tag}  [{r.get('config')}]"
            )

    roofline_section(probes)


def roofline_section(probes, depth=12):
    """VERDICT r4 #3: the measured roofline — device time per component
    of the flagship step with the lever that attacks each. Emits only
    when the component probes exist (scripts/perf_probe.py rows)."""
    by = {}
    for r in probes:
        n = r.get("probe")
        if not n:
            continue
        cur = by.get(n)
        # duplicates across watchdog re-runs: fastest (min ms) wins
        if cur is None or (r.get("ms_per_iter") or 1e18) < (
            cur.get("ms_per_iter") or 1e18
        ):
            by[n] = r

    peak = by.get("peak_matmul_bf16_8192", {}).get("tflops_per_sec")
    hbm = by.get("hbm_stream_bw", {}).get("gbytes_per_sec")
    step = next(
        (r for n, r in sorted(by.items()) if n.startswith("step_b")), None
    )
    comps = [
        ("attention layer x12", by.get("attn_layer_grad"), depth,
         "Pallas flash (scores stay in VMEM; AI 15 dense)"),
        ("GEGLU FF x12", by.get("ff_block_grad"), depth,
         "batch/fusion (AI 92 — near roofline already)"),
        ("logits head + CE", by.get("logits_head_grad"), 1,
         "fused_ce (vocab-chunked, no [B,N,V] materialization)"),
    ]
    if not (step or any(c[1] for c in comps)):
        return
    print("\n## Measured roofline (flagship geometry)\n")
    if peak:
        print(f"- achievable MXU peak: {peak} TFLOP/s bf16")
    if hbm:
        print(f"- achievable HBM stream bandwidth: {hbm} GB/s")
    if step:
        ms = step["ms_per_iter"]
        line = f"- full train step (zero-dispatch scan): {ms} ms/step"
        if step.get("tflops_per_sec") and peak:
            line += (
                f" = {step['tflops_per_sec']} TFLOP/s"
                f" = {step['tflops_per_sec'] / peak * 100:.1f}% of peak"
            )
        print(line)
    have = [(n, r, mult, lever) for n, r, mult, lever in comps if r]
    if have:
        print("\n| component | ms (x mult) | share of step | lever |")
        print("|---|---|---|---|")
        total = step["ms_per_iter"] if step else None
        acc = 0.0
        for name, r, mult, lever in have:
            ms = r["ms_per_iter"] * mult
            acc += ms
            share = f"{ms / total * 100:.0f}%" if total else "-"
            print(f"| {name} | {ms:.1f} | {share} | {lever} |")
        if total:
            resid = total - acc
            print(
                f"| other (embeds/norms/shift/opt/residual) | {resid:.1f} | "
                f"{resid / total * 100:.0f}% | XLA fusion; measure if large |"
            )


if __name__ == "__main__":
    main()
