"""Diagnose a real OpenAI dVAE / taming VQGAN checkpoint against this
framework's converters.

The in-repo golden tests for the pretrained-VAE bridges run against
synthetic checkpoints (the container has no egress to download the real
ones — `tests/test_openai_vae.py`), so the exact key layout of the
*released* files has never been seen by this code. This script is the
field diagnostic for that residual risk: point it at real files and it
validates structure inference, round-trips an encode/decode, and prints
shapes — BEFORE you spend a training run on it.

Usage:
  python scripts/check_pretrained_vae.py --openai ~/.cache/dalle
  python scripts/check_pretrained_vae.py --vqgan model.ckpt config.yaml
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _apply_platform_override():
    import os

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        import jax

        jax.config.update(
            "jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"]
        )


def check_openai(cache_dir: str) -> int:
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE

    print(f"loading OpenAI dVAE from {cache_dir} ...")
    try:
        vae = OpenAIDiscreteVAE(cache_dir=cache_dir)
    except FileNotFoundError as e:
        print(f"FAIL: {e}")
        return 1
    except Exception as e:
        print(f"FAIL: converter could not ingest the checkpoint structure: "
              f"{type(e).__name__}: {e}")
        print("-> please report this with the state-dict key listing")
        return 1

    print(f"  image_size={vae.image_size} num_layers={vae.num_layers} "
          f"num_tokens={vae.num_tokens}")
    img = jnp.zeros((1, vae.image_size, vae.image_size, 3), jnp.float32) + 0.5
    toks = vae.get_codebook_indices(img)
    print(f"  encode: {img.shape} -> tokens {toks.shape} "
          f"(range [{int(toks.min())}, {int(toks.max())}])")
    assert toks.shape[1] == (vae.image_size // (2 ** vae.num_layers)) ** 2
    out = vae.decode(toks)
    print(f"  decode: tokens -> {out.shape} "
          f"(range [{float(out.min()):.3f}, {float(out.max()):.3f}])")
    assert out.shape[1] == vae.image_size
    print("OK: OpenAI dVAE converter handles this checkpoint")
    return 0


def check_vqgan(model_path: str, config_path: str) -> int:
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.vae_io import VQGanVAE

    print(f"loading VQGAN from {model_path} ...")
    try:
        vae = VQGanVAE(model_path, config_path)
    except Exception as e:
        print(f"FAIL: {type(e).__name__}: {e}")
        return 1
    img = jnp.zeros((1, vae.image_size, vae.image_size, 3), jnp.float32) + 0.5
    toks = vae.get_codebook_indices(img)
    out = vae.decode(toks)
    print(f"  encode {img.shape} -> {toks.shape}; decode -> {out.shape}")
    print("OK: VQGAN converter handles this checkpoint")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--openai", metavar="CACHE_DIR",
                    help="directory holding encoder.pkl / decoder.pkl")
    ap.add_argument("--vqgan", nargs=2, metavar=("MODEL", "CONFIG"))
    args = ap.parse_args()
    if not args.openai and not args.vqgan:
        ap.error("pass --openai and/or --vqgan")
    _apply_platform_override()
    rc = 0
    if args.openai:
        rc |= check_openai(args.openai)
    if args.vqgan:
        rc |= check_vqgan(*args.vqgan)
    return rc


if __name__ == "__main__":
    sys.exit(main())
