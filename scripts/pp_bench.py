#!/usr/bin/env python
"""Pipeline-parallel trunk cost check: pipelined vs plain scan trunk.

VERDICT r4 weak #6: pp had engine-level parity tests but no hardware/cost
story. This bench times a DALLE training step (value_and_grad through the
full model) with the trunk run two ways:

  plain : the scan executor's lax.scan-over-depth trunk
  pp    : make_pipeline_trunk over a PP_N-stage 'pp' mesh with PP_MICRO
          microbatches (parallel/gpipe.py GPipe schedule)

On ONE chip (PP_N=1) the difference is the pure cost of the schedule
machinery (shard_map + microbatch scan + ppermute plumbing) — the number
that says whether pp=1 degenerates gracefully. On the 8-device CPU mesh
(PP_N=4/8) it measures schedule overhead including bubble
(PP_MICRO/(PP_MICRO+PP_N-1) ideal efficiency).

Env: PP_N (stages, default 1), PP_MICRO (default 4), PP_BATCH (8),
PP_FMAP (16), PP_DIM (512), PP_DEPTH (8), PP_RUNS (3), PP_TEXT (64).
Defaults are sized to run everywhere; the TPU matrix row pins the
flagship geometry. Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    import jax

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.models.transformer import (
        Transformer,
        make_pipeline_trunk,
    )
    from dalle_pytorch_tpu.parallel.gpipe import make_pp_mesh

    pp_n = int(os.environ.get("PP_N", "1"))
    n_micro = int(os.environ.get("PP_MICRO", "4"))
    batch = int(os.environ.get("PP_BATCH", "8"))
    fmap = int(os.environ.get("PP_FMAP", "16"))
    dim = int(os.environ.get("PP_DIM", "512"))
    depth = int(os.environ.get("PP_DEPTH", "8"))
    runs = int(os.environ.get("PP_RUNS", "3"))
    text_seq = int(os.environ.get("PP_TEXT", "64"))

    model = DALLE(
        dim=dim, depth=depth, heads=max(dim // 64, 1), dim_head=64,
        num_image_tokens=8192, image_fmap_size=fmap,
        num_text_tokens=10000, text_seq_len=text_seq,
        shift_tokens=True, rotary_emb=True, executor="scan",
        dtype=jnp.bfloat16,
    )
    text = jnp.ones((batch, text_seq), jnp.int32)
    toks = jnp.zeros((batch, fmap * fmap), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, toks)["params"]

    mesh = make_pp_mesh(pp_n)
    pipelined = make_pipeline_trunk(
        Transformer(**model.transformer_kwargs()), mesh, n_micro=n_micro
    )

    def loss_plain(p):
        loss, _ = model.apply({"params": p}, text, toks, return_loss=True)
        return loss

    def loss_pp(p):
        trunk = lambda h: pipelined(p["transformer"], h)
        loss, _ = model.apply(
            {"params": p}, text, toks, return_loss=True, trunk_fn=trunk
        )
        return loss

    def timed(fn):
        g = jax.jit(jax.value_and_grad(fn))
        l, grads = g(params)  # compile
        float(l)
        ts = []
        for _ in range(runs):
            t0 = time.perf_counter()
            l, grads = g(params)
            # forced readback: block_until_ready is a no-op on the tunnel
            float(l)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2], float(l)

    t_plain, l_plain = timed(loss_plain)
    t_pp, l_pp = timed(loss_pp)

    out = {
        "metric": "pp_trunk_step_overhead",
        "value": round(t_pp / t_plain, 3),
        "unit": "x_plain",
        "ok": abs(l_pp - l_plain) < 1e-2 * max(1.0, abs(l_plain)),
        "vs_baseline": None,  # reference has no pipeline parallelism
        "plain_s": round(t_plain, 4),
        "pp_s": round(t_pp, 4),
        "pp": pp_n,
        "n_micro": n_micro,
        "ideal_bubble_eff": round(n_micro / (n_micro + pp_n - 1), 3),
        "loss_delta": round(abs(l_pp - l_plain), 6),
        "device": jax.devices()[0].device_kind,
        "config": f"dim{dim}-depth{depth}-fmap{fmap}-bs{batch}-bf16",
    }
    if jax.devices()[0].platform == "cpu":
        out["fallback"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    main()
