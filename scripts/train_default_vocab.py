"""Train the shipped default BPE vocabulary.

The reference vendors a 262k-line CLIP BPE vocab so `get_tokenizer()` works
out of the box (`/root/reference/dalle_pytorch/tokenizer.py:64-68`,
`data/bpe_simple_vocab_16e6.txt`). This repo's equivalent: an 8k-token
model trained with the in-repo native C++ BPE on text available inside the
image — every rainbow caption (the built-in synthetic dataset) plus
public-domain/permissive English prose (Python stdlib docstrings, installed
package METADATA/README text, Debian copyright files) — committed as
`dalle_pytorch_tpu/data/default_bpe_8k.model` (~100 KB).

Rerun to regenerate:  python scripts/train_default_vocab.py [vocab_size]

`vocab_size` defaults to 8192 -> `default_bpe_8k.model`; pass 32768 to
regenerate the CLIP-scale `default_bpe_32k.model` (preferred by
`get_tokenizer()` when present), which also widens the corpus with
docstring prose from installed site-packages (numpy/scipy/jax etc.).
"""

from __future__ import annotations

import ast
import glob
import hashlib
import io
import os
import sys
import tokenize
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "dalle_pytorch_tpu" / "data" / "default_bpe_8k.model"
VOCAB_SIZE = 8192


def rainbow_captions() -> list[str]:
    from dalle_pytorch_tpu.data.rainbow import RainbowDataset

    ds = RainbowDataset()
    return [ds.caption(i) for i in range(len(ds))]


def stdlib_docstrings(limit_files: int = 400) -> list[str]:
    """English prose from Python's own (PSF-licensed) stdlib docstrings."""
    out = []
    stdlib = Path(os.path.dirname(os.__file__))
    files = sorted(stdlib.glob("*.py"))[:limit_files]
    for f in files:
        try:
            tree = ast.parse(f.read_text(errors="ignore"))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                doc = ast.get_docstring(node)
                if doc and len(doc) > 40:
                    out.append(doc)
    return out


def site_packages_docstrings(cap_bytes: int = 30_000_000) -> list[str]:
    """Docstring prose from installed packages (numpy/scipy/jax etc.).

    Only used for the 32k vocabulary: the 8k corpus alone is too small to
    support 32k distinct merges without a long tail of junk tokens.
    """
    out, total = [], 0
    roots = sorted(glob.glob(os.path.join(sys.prefix, "lib/*/site-packages/*/")))
    for root in roots:
        for f in sorted(Path(root).rglob("*.py")):
            try:
                tree = ast.parse(f.read_text(errors="ignore"))
            except (SyntaxError, OSError, ValueError):
                continue
            for node in ast.walk(tree):
                if isinstance(
                    node,
                    (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
                ):
                    doc = ast.get_docstring(node)
                    if doc and len(doc) > 60:
                        out.append(doc)
                        total += len(doc)
            if total > cap_bytes:
                return out
    return out


def package_metadata(cap_bytes: int = 4_000_000) -> list[str]:
    """Long-description prose from installed package METADATA files."""
    out, total = [], 0
    for f in sorted(glob.glob(os.path.join(sys.prefix, "lib/*/site-packages/*.dist-info/METADATA"))):
        try:
            text = Path(f).read_text(errors="ignore")
        except OSError:
            continue
        # skip the header block (key: value lines), keep the body prose
        body = text.split("\n\n", 1)
        body = body[1] if len(body) == 2 else ""
        if len(body) < 200:
            continue
        out.append(body)
        total += len(body)
        if total > cap_bytes:
            break
    return out


def debian_copyright(cap_files: int = 60) -> list[str]:
    """Debian copyright texts, deduplicated by content hash."""
    seen, out = set(), []
    for f in sorted(glob.glob("/usr/share/doc/*/copyright")):
        try:
            text = Path(f).read_text(errors="ignore")
        except OSError:
            continue
        h = hashlib.sha1(text.encode()).hexdigest()
        if h in seen:
            continue
        seen.add(h)
        out.append(text)
        if len(out) >= cap_files:
            break
    return out


def main():
    vocab_size = int(sys.argv[1]) if len(sys.argv) > 1 else VOCAB_SIZE
    out = (
        REPO / "dalle_pytorch_tpu" / "data" / f"default_bpe_{vocab_size // 1024}k.model"
    )
    parts = []
    caps = rainbow_captions()
    # repeat the captions so the target domain outweighs incidental prose
    parts.extend(caps * 20)
    docs = stdlib_docstrings()
    parts.extend(docs)
    meta = package_metadata()
    parts.extend(meta)
    deb = debian_copyright()
    parts.extend(deb)
    sp: list[str] = []
    if vocab_size > 16384:
        sp = site_packages_docstrings()
        parts.extend(sp)
    corpus = "\n".join(parts)
    print(
        f"corpus: {len(caps)} captions x20, {len(docs)} docstrings, "
        f"{len(meta)} package bodies, {len(deb)} copyright files, "
        f"{len(sp)} site-package docstrings -> {len(corpus) / 1e6:.1f} MB"
    )

    from dalle_pytorch_tpu.data.native_bpe import NativeBPE

    bpe = NativeBPE.train(corpus, vocab_size=vocab_size)
    bpe.save(out)
    print(f"trained vocab_size={bpe.vocab_size} -> {out} ({out.stat().st_size} bytes)")

    # smoke: round-trip a caption and some prose
    for text in [caps[0], "a quick brown fox jumps over the lazy dog"]:
        ids = bpe.encode(text)
        assert bpe.decode(ids) == text, (text, bpe.decode(ids))
        print(f"  {len(text)} chars -> {len(ids)} tokens: {text[:50]!r}")


if __name__ == "__main__":
    main()
