#!/bin/bash
# Round-3 TPU experiment matrix. Runs every perf configuration back to back
# and appends one JSON line per result to TPU_RESULTS.jsonl. Each step is
# individually time-boxed so one wedge cannot eat the whole matrix.
# Usage: bash scripts/run_tpu_experiments.sh [out_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-TPU_RESULTS.jsonl}"

run() {
    local name="$1"; shift
    local tmo="$1"; shift
    echo "=== $name (timeout ${tmo}s) ===" >&2
    local line
    line=$(timeout "$tmo" env "$@" 2>/dev/null | grep '^{' | tail -5)
    if [ -n "$line" ]; then
        while IFS= read -r l; do
            echo "{\"experiment\": \"$name\", \"result\": $l}" >> "$OUT"
        done <<< "$line"
        echo "$line" >&2
    else
        echo "{\"experiment\": \"$name\", \"result\": null}" >> "$OUT"
        echo "(no output)" >&2
    fi
}

# 0. component probes: peak MXU rate + per-block costs
run probe_peak        900 PROBE_K=8 python scripts/perf_probe.py peak
run probe_components 1200 PROBE_K=8 python scripts/perf_probe.py attn ff logits

# 1. bench ladder: remat policy, flash attention, fused CE
run bench_base       1200 python bench.py
run bench_policy     1200 BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable python bench.py
run bench_flash      1200 BENCH_ATTN=flash python bench.py
run bench_flash_pol  1200 BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable python bench.py
run bench_flash_pol_ce 1200 BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py
run bench_noremat_a2 1200 BENCH_REMAT=0 BENCH_ACCUM=2 BENCH_ATTN=flash python bench.py
run bench_host_input 1200 BENCH_INPUT=host BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable python bench.py

# 2. pallas on-chip validation: compiled parity + dense-vs-flash A/B
run pallas_onchip    1800 PROBE_K=8 python scripts/pallas_onchip.py

# 3. inference north star
run generate_p50     1800 python bench_generate.py

echo "results -> $OUT" >&2
