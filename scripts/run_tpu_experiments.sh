#!/bin/bash
# Round-3 TPU experiment matrix. Runs every perf configuration back to back
# and appends one JSON line per result to TPU_RESULTS.jsonl. Each step is
# individually time-boxed so one wedge cannot eat the whole matrix.
# Usage: bash scripts/run_tpu_experiments.sh [out_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-TPU_RESULTS.jsonl}"

run() {
    local name="$1"; shift
    local tmo="$1"; shift
    echo "=== $name (timeout ${tmo}s) ===" >&2
    local line
    line=$(timeout "$tmo" env "$@" 2>/dev/null | grep '^{' | tail -5)
    if [ -n "$line" ]; then
        while IFS= read -r l; do
            echo "{\"experiment\": \"$name\", \"result\": $l}" >> "$OUT"
        done <<< "$line"
        echo "$line" >&2
    else
        echo "{\"experiment\": \"$name\", \"result\": null}" >> "$OUT"
        echo "(no output)" >&2
    fi
}

# Persistent compile cache: retries after a tunnel drop shouldn't pay
# (or re-trigger) the same giant remote compile twice, if the backend
# honors client-side executable caching.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

# Ordered most-valuable-first: the tunnel relay has died mid-matrix twice
# (both times around a large remote compile), so the headline numbers must
# land before the nice-to-haves.

# 0. cheapest probe first: peak MXU rate (tiny compile, validates tunnel)
run probe_peak        600 PROBE_K=8 python scripts/perf_probe.py peak

# 1. headline bench. bench.py's internal profile ladder already tries
# flash+policy+fused_ce first and falls back to dense; one call does it.
run bench_main       2400 BENCH_NO_EXTRA=1 python bench.py

# 1b. multi-step dispatch-amortization A/B (r4: dense and flash single-
# step programs measured the SAME ~2s/step wall — the signature of a
# fixed per-dispatch cost on the synchronous tunnel). steps8 flash vs
# steps8 dense vs the single-step rows separates dispatch overhead from
# program time quantitatively.
run bench_steps8_flash 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
run bench_steps8_dense 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_EXECUTOR=scan BENCH_ATTN=dense BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
run bench_steps16_flash 1200 BENCH_SCAN_STEPS=16 BENCH_STEPS=32 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
run bench_steps32_flash 1200 BENCH_SCAN_STEPS=32 BENCH_STEPS=64 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
# amortization x larger per-dispatch work: batch 32 lifts FF/logits
# arithmetic intensity on top of the RTT amortization
run bench_steps8_b32 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_BATCH=32 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
# device-time levers on top of the amortized dispatch: full-recompute
# remat (policy's FLOP saving quantified — under flash the attention
# dots are Pallas-internal, so dot POLICIES only differ on the FF/logits
# projections; the real A/B is policy vs none), and no remat at
# microbatch 8 (zero recompute, 2x accumulation)
run bench_steps8_fullremat 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=none BENCH_FUSED_CE=1 python bench.py --child
run bench_steps8_noremat_a2 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_REMAT=0 BENCH_ACCUM=2 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_FUSED_CE=1 python bench.py --child
# real host input under 8-step windows: whole [8,B,...] windows are
# assembled+transferred per dispatch — input_wait_frac shows whether the
# host pipeline keeps up with the burstier demand
run bench_steps8_host 1200 BENCH_SCAN_STEPS=8 BENCH_STEPS=32 BENCH_INPUT=host BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child

# 1c. on-device step probe: K steps inside ONE jit (zero per-step
# dispatch) — the pure device-time denominator for the overhead split
run probe_step       1500 PROBE_K=8 python scripts/perf_probe.py step

# 2. inference north star (scan decode A/B later in the matrix)
run generate_p50     1500 python bench_generate.py
# 2b. phase split (prefill vs decode scan vs dVAE pixel decode) — where
# to attack the r4-banked 3.222s p50 (target: <=2s/batch-of-4)
run generate_breakdown 1500 GEN_PHASES=1 python bench_generate.py --child
# 2c. batch amortization lever: per-token decode is param-read bound
# (~300MB of bf16 weights re-read per token); batch 16 amortizes those
# reads 4x over batch 4 — tokens/s should scale far better than linearly
# in wall time if the param-bound model is right
run generate_b16     1500 GEN_BATCH=16 python bench_generate.py --child
# 2d. end-to-end-pixels: dVAE decode fused into the sampler program —
# one dispatch for tokens AND pixels (saves a full tunnel RTT/batch)
run generate_fused   1500 GEN_FUSED=1 python bench_generate.py --child

# 4. per-component costs (attn/ff/logits AI table)
run probe_components 1200 PROBE_K=8 python scripts/perf_probe.py hbm attn ff logits

# 5. secondary bench A/Bs. `--child` pins the exact configuration: the
# guard's profile ladder applies env with setdefault, so a pinned env
# would make every fallback profile rerun the same config under a wrong
# label. An A/B row that fails should record null, not masquerade.
run bench_scan_exec  1200 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
run bench_unrolled_flash 1200 BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
run bench_base       1200 python bench.py --child
run bench_noremat_a2 1200 BENCH_REMAT=0 BENCH_ACCUM=2 BENCH_ATTN=flash python bench.py --child
run bench_host_input 1200 BENCH_INPUT=host BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable python bench.py --child
# larger global batch: flash frees the score tensors, so 32 may fit and
# lift arithmetic intensity on the FF/logits blocks
run bench_scan_b32   1200 BENCH_BATCH=32 BENCH_EXECUTOR=scan BENCH_ATTN=flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
# jax library TPU flash kernel in the full train step (vs in-repo flash)
run bench_scan_libflash 1200 BENCH_EXECUTOR=scan BENCH_ATTN=lib_flash BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child
# sparse attn-type cycle (the reference's axial/conv pattern) under the
# scan executor: dense + depth-stacked pattern masks — is masked-dense
# cheaper than full dense at seq 1280 on chip?
run bench_scan_axial 1200 BENCH_EXECUTOR=scan BENCH_ATTN=dense BENCH_ATTN_TYPES=full,axial_row,axial_col,conv_like BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable BENCH_FUSED_CE=1 python bench.py --child

# scan-native cached decode vs the unrolled decode program
run generate_p50_scan 1200 GEN_EXECUTOR=scan python bench_generate.py --child

# pipeline-parallel trunk cost check at flagship geometry: pp=1 on one
# chip = pure schedule-machinery overhead (CPU-mesh datum: 0.95x plain,
# i.e. free; the multi-stage schedule itself is covered by the 8-dev CPU
# parity suite). A value near 1.0 clears pp for production use.
run bench_pp1        1200 PP_N=1 PP_MICRO=4 PP_BATCH=16 PP_FMAP=32 PP_DIM=1024 PP_DEPTH=12 PP_TEXT=256 python scripts/pp_bench.py

# 6. notebook-scale rainbow convergence (VERDICT r3 weak #8: the CPU
# proxy is 16 samples; the reference notebook bar is 1.0 train exact at
# ~9k samples). Last in the matrix: longest and least perf-critical.
# steps-per-dispatch 16: at ~2s dispatch RTT the 5500 per-step round
# trips alone would be ~3h; windowed it fits the time box
run rainbow_convergence 3000 python examples/rainbow_dalle.py \
    --num-samples 9216 --vae-steps 1500 --dalle-steps 4000 \
    --batch-size 64 --eval-samples 64 --steps-per-dispatch 16 \
    --out-dir rainbow_tpu_out

# 7. LAST: pallas isolated-kernel validation (compiled parity +
# dense-vs-flash A/B). Its Mosaic compile has preceded two relay deaths
# and once ate 21 min without emitting a row — nothing of value may be
# scheduled after it. The in-train-step flash-vs-dense answer comes from
# the bench_steps8 rows above regardless.
run pallas_onchip    1500 PROBE_K=8 python scripts/pallas_onchip.py

echo "results -> $OUT" >&2
