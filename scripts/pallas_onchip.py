"""On-chip Pallas flash-attention validation: parity + dense-vs-flash A/B.

VERDICT r2 weak #2: every Pallas claim so far ran in interpret mode. This
script must run on the real TPU; it

  1. checks the compiled kernel's numerics against the dense oracle at the
     flagship and long-context geometries (fwd AND grad),
  2. times dense vs flash (fwd+bwd) at seq 1280 / 2048 / 4096 with the
     loop-inside-jit pattern (one dispatch, K iterations, scalar readback),
  3. prints one JSON line per row for BASELINE.md.

Run: python scripts/pallas_onchip.py            (TPU via tunnel)
     PROBE_PLATFORM=cpu python scripts/...      (interpret smoke)
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

K = int(os.environ.get("PROBE_K", "8"))
SEQS = [int(s) for s in os.environ.get("PROBE_SEQS", "1280,2048,4096").split(",")]
BATCH = int(os.environ.get("PROBE_BATCH", "4"))
HEADS = int(os.environ.get("PROBE_HEADS", "16"))
DIM_HEAD = int(os.environ.get("PROBE_DIM_HEAD", "64"))


def main():
    import jax

    if os.environ.get("PROBE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["PROBE_PLATFORM"])
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from dalle_pytorch_tpu.ops.attention_core import dense_attention
    from dalle_pytorch_tpu.ops.pallas_attention import (
        _use_interpret,
        flash_attention,
    )

    dev = jax.devices()[0].device_kind
    interpret = _use_interpret()
    print(
        json.dumps(
            {"probe": "env", "device": dev, "interpret_mode": interpret}
        ),
        flush=True,
    )

    def qkv(seq, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), 3)
        shape = (BATCH, HEADS, seq, DIM_HEAD)
        return tuple(
            jax.random.normal(k, shape, jnp.bfloat16) * 0.5 for k in ks
        )

    # ---- 1. compiled parity vs dense oracle (fwd + grad) ----
    for seq in SEQS[:2]:  # parity at the two smaller geometries
        q, k, v = qkv(seq)
        causal = jnp.tril(jnp.ones((seq, seq), bool))[None, None]

        out_f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
            q, k, v
        )
        out_d = jax.jit(lambda q, k, v: dense_attention(q, k, v, mask=causal))(
            q, k, v
        )
        err = float(
            jnp.max(jnp.abs(out_f.astype(jnp.float32) - out_d.astype(jnp.float32)))
        )

        def loss_f(q):
            return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

        def loss_d(q):
            return dense_attention(q, k, v, mask=causal).astype(jnp.float32).sum()

        gf = jax.jit(jax.grad(loss_f))(q)
        gd = jax.jit(jax.grad(loss_d))(q)
        gerr = float(
            jnp.max(jnp.abs(gf.astype(jnp.float32) - gd.astype(jnp.float32)))
        )
        rec = {
            "probe": "parity",
            "seq": seq,
            "max_abs_err_fwd": round(err, 5),
            "max_abs_err_grad_q": round(gerr, 5),
            "ok": bool(err < 2e-2 and gerr < 2e-1),
        }
        print(json.dumps(rec), flush=True)

    # ---- 2. dense vs flash timing (fwd+bwd), loop-inside-jit ----
    def timed_grad(attn_fn, seq):
        q, k, v = qkv(seq)

        def loss(q):
            return attn_fn(q, k, v).astype(jnp.float32).mean()

        g = jax.grad(loss)

        @jax.jit
        def loop(q):
            def body(_, q):
                return q - 1e-3 * g(q).astype(q.dtype)

            return lax.fori_loop(0, K, body, q)

        out = loop(q)
        _ = float(jnp.asarray(out).ravel()[0])
        t0 = time.perf_counter()
        out = loop(q)
        _ = float(jnp.asarray(out).ravel()[0])
        return (time.perf_counter() - t0) / K

    # ---- 2b. flash block-size sweep at the flagship seq (tuning data;
    # PROBE_BLOCKS="bq:bk,..." to override) ----
    blocks = [
        tuple(int(x) for x in spec.split(":"))
        for spec in os.environ.get(
            "PROBE_BLOCKS", "128:128,256:128,128:256,256:256,512:128"
        ).split(",")
    ]
    for bq, bk in blocks:
        row = {"probe": "block_sweep", "seq": SEQS[0], "bq": bq, "bk": bk}
        try:
            row["flash_ms"] = round(
                timed_grad(
                    lambda q, k, v: flash_attention(
                        q, k, v, causal=True, block_q=bq, block_k=bk
                    ),
                    SEQS[0],
                )
                * 1e3,
                2,
            )
        except Exception as e:
            row["flash_ms"] = None
            row["error"] = type(e).__name__
        print(json.dumps(row), flush=True)

    # ---- 2c. jax library TPU flash kernel (pallas.ops.tpu.flash_attention)
    # as a second baseline: if it beats the in-repo kernel on-chip, adopt
    # it behind attn_impl. Skipped silently off-TPU (it is TPU-only).
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as lib_flash,
        )

        for seq in SEQS:
            row = {"probe": "lib_flash", "seq": seq, "batch": BATCH}
            try:
                row["lib_flash_ms"] = round(
                    timed_grad(
                        lambda q, k, v: lib_flash(q, k, v, causal=True), seq
                    )
                    * 1e3,
                    2,
                )
            except Exception as e:
                row["lib_flash_ms"] = None
                row["error"] = type(e).__name__
            print(json.dumps(row), flush=True)
    except ImportError:
        pass

    # ---- 2d. jax splash-attention kernel (the MaxText production kernel)
    # — fwd+bwd timing on real hardware only: its backward miscompiles in
    # CPU interpret mode (jax 0.9 interpret-machinery bug), so there is no
    # off-chip smoke for it; a model-level attn_impl would follow only if
    # this row beats flash/lib_flash on-chip.
    if not interpret:
        try:
            from jax.experimental.pallas.ops.tpu.splash_attention import (
                splash_attention_kernel as sk,
                splash_attention_mask as sm,
            )

            for seq in SEQS:
                row = {"probe": "splash", "seq": seq, "batch": BATCH}
                kernel = sk.make_splash_mha(
                    sm.MultiHeadMask([sm.CausalMask((seq, seq))] * HEADS),
                    head_shards=1,
                    q_seq_shards=1,
                )
                scale = DIM_HEAD**-0.5
                fn = jax.vmap(lambda q, k, v: kernel(q * scale, k, v))
                try:
                    row["splash_ms"] = round(
                        timed_grad(lambda q, k, v: fn(q, k, v), seq) * 1e3, 2
                    )
                except Exception as e:
                    row["splash_ms"] = None
                    row["error"] = type(e).__name__
                print(json.dumps(row), flush=True)
        except ImportError:
            pass

    for seq in SEQS:
        causal = jnp.tril(jnp.ones((seq, seq), bool))[None, None]
        row = {"probe": "ab", "seq": seq, "batch": BATCH}
        try:
            row["dense_ms"] = round(
                timed_grad(
                    lambda q, k, v: dense_attention(q, k, v, mask=causal), seq
                )
                * 1e3,
                2,
            )
        except Exception as e:  # dense OOMs first at long seq
            row["dense_ms"] = None
            row["dense_error"] = type(e).__name__
        try:
            row["flash_ms"] = round(
                timed_grad(
                    lambda q, k, v: flash_attention(q, k, v, causal=True), seq
                )
                * 1e3,
                2,
            )
        except Exception as e:
            row["flash_ms"] = None
            row["flash_error"] = type(e).__name__
        if row.get("dense_ms") and row.get("flash_ms"):
            row["flash_speedup"] = round(row["dense_ms"] / row["flash_ms"], 2)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
