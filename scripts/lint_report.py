#!/usr/bin/env python
"""One-line JSON tracelint report for dashboards and CI log scraping.

Runs the full rule pack (or --select'ed codes) over the package (or
explicit paths) and prints a SINGLE json line:

    {"files": 74, "findings": 0, "suppressed": 10, "baselined": 0,
     "rc": 0, "per_rule": {"TL001": 0, ..., "TL021": 0},
     "suppressed_per_rule": {"TL002": 9, ...}}

`per_rule` carries EVERY registered rule code (zeros included) so a
rule silently dropping out of the pack shows up as a missing key in
diffs, not as an indistinguishable zero. Exit code is the usual
tracelint severity bitmask (0 clean, 1 errors, 4 warning-tier, 5 both).

    python scripts/lint_report.py
    python scripts/lint_report.py --select TL017,TL018,TL019,TL020,TL021
    python scripts/lint_report.py dalle_pytorch_tpu/serving/
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from dalle_pytorch_tpu.analysis.lint import (  # noqa: E402
    PACKAGE_DIR,
    exit_code,
    lint_paths,
)
from dalle_pytorch_tpu.analysis.rules import ALL_RULES  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("paths", nargs="*", type=Path)
    parser.add_argument(
        "--select", default=None, metavar="TLxxx[,TLxxx...]",
        help="restrict to these rule codes",
    )
    args = parser.parse_args(argv)

    select = None
    if args.select:
        select = {c.strip() for c in args.select.split(",") if c.strip()}
        known = {r.code for r in ALL_RULES} | {"TL000"}
        unknown = select - known
        if unknown:
            print(f"unknown rule code(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    try:
        result = lint_paths(args.paths or [PACKAGE_DIR], select=select)
    except FileNotFoundError as exc:
        print(f"lint_report: {exc}", file=sys.stderr)
        return 2

    codes = sorted(
        r.code for r in ALL_RULES if select is None or r.code in select
    )
    per_rule = {code: 0 for code in codes}
    for f in result.findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    suppressed_per_rule: dict = {}
    for f, _sup in result.suppressed:
        suppressed_per_rule[f.rule] = suppressed_per_rule.get(f.rule, 0) + 1

    rc = exit_code(result)
    print(json.dumps({
        "files": result.files_checked,
        "findings": len(result.findings),
        "suppressed": len(result.suppressed),
        "baselined": len(result.baselined),
        "rc": rc,
        "per_rule": per_rule,
        "suppressed_per_rule": dict(sorted(suppressed_per_rule.items())),
    }, sort_keys=False))
    return rc


if __name__ == "__main__":
    sys.exit(main())
