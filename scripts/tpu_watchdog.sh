#!/bin/bash
# Polls the tunneled TPU; the moment a probe matmul succeeds, runs the
# round-3 experiment matrix once and exits. Detach with:
#   nohup setsid bash scripts/tpu_watchdog.sh > watchdog.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE='import jax, jax.numpy as jnp; x = jnp.ones((8,8)) @ jnp.ones((8,8)); print("PROBE_OK", float(x.sum()))'

echo "[watchdog] started $(date -u +%H:%M:%S)"
DEADLINE=$(( $(date +%s) + ${WATCHDOG_MAX_S:-18000} ))  # stop polling after 5h
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if timeout 90 python -c "$PROBE" 2>/dev/null | grep -q PROBE_OK; then
        echo "[watchdog] tunnel recovered at $(date -u +%H:%M:%S); running matrix"
        bash scripts/run_tpu_experiments.sh TPU_RESULTS.jsonl
        echo "[watchdog] matrix done at $(date -u +%H:%M:%S)"
        exit 0
    fi
    echo "[watchdog] $(date -u +%H:%M:%S) tunnel still down"
    sleep 240
done
echo "[watchdog] giving up at $(date -u +%H:%M:%S) (deadline reached)"
