#!/bin/bash
# Polls the tunneled TPU; each time a probe matmul succeeds, runs the
# experiment matrix, then RE-ARMS (up to WATCHDOG_MAX_RUNS) — a tunnel
# that flaps mid-matrix gets its remaining rows on the next window
# instead of wasting it (the summarizer dedupes repeated rows, best
# result wins). Detach with:
#   nohup setsid bash scripts/tpu_watchdog.sh > watchdog.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE='import jax, jax.numpy as jnp; x = jnp.ones((8,8)) @ jnp.ones((8,8)); print("PROBE_OK", float(x.sum()))'

echo "[watchdog] started $(date -u +%H:%M:%S)"
DEADLINE=$(( $(date +%s) + ${WATCHDOG_MAX_S:-18000} ))  # stop polling after 5h
RUNS=0
MAX_RUNS=${WATCHDOG_MAX_RUNS:-3}
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if timeout 90 python -c "$PROBE" 2>/dev/null | grep -q PROBE_OK; then
        if [ "$RUNS" -ge 1 ] && ! grep -q '"result": null' TPU_RESULTS.jsonl 2>/dev/null; then
            # previous run completed every row — nothing left to retry
            echo "[watchdog] matrix complete (no null rows); exiting"
            exit 0
        fi
        RUNS=$((RUNS + 1))
        echo "[watchdog] tunnel up at $(date -u +%H:%M:%S); matrix run $RUNS/$MAX_RUNS"
        bash scripts/run_tpu_experiments.sh TPU_RESULTS.jsonl
        echo "[watchdog] matrix run $RUNS done at $(date -u +%H:%M:%S)"
        if [ "$RUNS" -ge "$MAX_RUNS" ]; then
            echo "[watchdog] max runs reached; exiting"
            exit 0
        fi
        # brief cool-down, then keep polling: a run truncated by a tunnel
        # death leaves null rows, which the next window retries
        sleep 120
    else
        echo "[watchdog] $(date -u +%H:%M:%S) tunnel still down"
        # short poll gap: observed tunnel windows are ~35 min and the 90s
        # hang-probe already bounds the cost of a dead relay
        sleep 150
    fi
done
echo "[watchdog] giving up at $(date -u +%H:%M:%S) (deadline reached)"
