"""Predicted-HBM ladder: XLA cost analysis of the bench configurations.

The round-3 finding (BASELINE.md) is that the flagship step is
HBM-bandwidth-bound, so the *bytes accessed* of the compiled program is
the best hardware-free predictor of which configuration wins. This script
AOT-compiles the real train step (CPU backend — same HLO structure as
TPU for everything except the Pallas flash kernel) at FULL flagship
depth, reads `compiled.cost_analysis()`, and prints one JSON line per
config with FLOPs, bytes, arithmetic intensity, and the
bandwidth-implied MFU ceiling on a v5e (197 TFLOP/s peak, ~819 GB/s HBM).

IMPORTANT measurement caveat: XLA cost analysis counts `lax.scan` /
while-loop bodies ONCE, not x trip-count, so any config containing a
loop (scan executor, vocab-chunked fused CE, grad accumulation)
undercompares. Only loop-free configurations are compiled here; the
flash and fused-CE levers are applied as clearly-labeled analytic
adjustments with stated assumptions:
  * flash: per-layer [B, H, N, N] bf16 score traffic (4 passes/step with
    selective remat: fwd write+read, bwd recompute write+read) replaced
    by linear q/k/v/o+lse traffic;
  * fused CE: two fp32 [B, N, V] logits materializations (fwd + bwd
    softmax-minus-onehot) replaced by chunked transients that never
    leave VMEM.

Usage: python scripts/hbm_model.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# shared with the live serving-side accounting (obs/vitals.py:
# ProgramCostTable) so offline and live rooflines cannot drift
from dalle_pytorch_tpu.obs.vitals import (  # noqa: E402
    V5E_HBM_BPS, V5E_PEAK_FLOPS, extract_cost,
)

DIM, DEPTH, HEADS, DIM_HEAD = 1024, 12, 16, 64
TEXT_SEQ, FMAP, BATCH = 256, 32, 16
SEQ = TEXT_SEQ + FMAP * FMAP
VOCAB = 10000 + TEXT_SEQ + 8192  # model.total_tokens at this geometry


def build_step(mode, remat_policy):
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.training import (
        TrainState, make_optimizer, make_dalle_train_step,
    )

    model = DALLE(
        dim=DIM, depth=DEPTH, heads=HEADS, dim_head=DIM_HEAD,
        num_image_tokens=8192, image_fmap_size=FMAP,
        num_text_tokens=10000, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True, attn_impl="dense",
        reversible=True, reversible_impl="remat", remat_policy=remat_policy,
        fused_ce=False, executor="unrolled", dtype=jnp.bfloat16,
    )
    text = jnp.ones((BATCH, TEXT_SEQ), jnp.int32)
    tokens = jnp.zeros((BATCH, FMAP * FMAP), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0), text, tokens)[
        "params"
    ]
    params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(3e-4, clip_grad_norm=0.5),
    )
    step = make_dalle_train_step(model, mode=mode)
    return step, state, {"text": text, "image_tokens": tokens}


def emit(row):
    print(json.dumps(row), flush=True)
    return row


def ceiling(flops, nbytes):
    ai = flops / max(nbytes, 1.0)
    return ai, min(1.0, ai * V5E_HBM_BPS / V5E_PEAK_FLOPS)


def analyze(name, mode, remat_policy):
    import jax

    t0 = time.time()
    step, state, batch = build_step(mode, remat_policy)
    compiled = jax.jit(step, donate_argnums=0).lower(
        state, batch, jax.random.PRNGKey(1)
    ).compile()
    cost = extract_cost(compiled)
    flops = float(cost.get("flops", 0.0))
    nbytes = float(cost.get("bytes accessed", 0.0))
    ai, mfu = ceiling(flops, nbytes)
    return emit({
        "config": name,
        "mode": mode,
        "flops_per_step_T": round(flops / 1e12, 2),
        "gbytes_per_step": round(nbytes / 1e9, 1),
        "flop_per_byte": round(ai, 1),
        "bw_implied_mfu_ceiling": round(mfu, 3),
        "compile_s": round(time.time() - t0, 1),
        "measured": "xla_cost_analysis",
    })


def adjust(row, name, delta_bytes, note):
    """Analytic lever on top of a compiled row: bytes shift, FLOPs kept."""
    flops = row["flops_per_step_T"] * 1e12
    nbytes = row["gbytes_per_step"] * 1e9 + delta_bytes
    ai, mfu = ceiling(flops, nbytes)
    return emit({
        "config": name,
        "mode": row["mode"],
        "flops_per_step_T": row["flops_per_step_T"],
        "gbytes_per_step": round(nbytes / 1e9, 1),
        "flop_per_byte": round(ai, 1),
        "bw_implied_mfu_ceiling": round(mfu, 3),
        "measured": "analytic_on_" + row["config"],
        "note": note,
    })


def measure_attention_chain():
    """Per-layer op-level bytes of the dense score chain (fwd+bwd), same
    metric as the full-step rows — the part flash keeps in VMEM."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.ops.attention_core import dense_attention
    import numpy as np

    q = jnp.zeros((BATCH, HEADS, SEQ, DIM_HEAD), jnp.bfloat16)
    mask = jnp.asarray(np.tril(np.ones((SEQ, SEQ), bool)))[None, None]

    def f(q, k, v):
        return dense_attention(q, k, v, mask=mask).astype(jnp.float32).sum()

    compiled = jax.jit(jax.grad(f, argnums=(0, 1, 2))).lower(q, q, q).compile()
    total = float(extract_cost(compiled).get("bytes accessed", 0.0))
    # flash's true per-layer traffic for the same math: q/k/v in, o out
    # (fwd), q/k/v/o/do in, dq/dk/dv out (bwd) + lse/delta rows
    linear = 12 * BATCH * HEADS * SEQ * DIM_HEAD * 2 + 3 * BATCH * HEADS * SEQ * 4
    emit({
        "component": "dense_score_chain_per_layer",
        "gbytes_fwd_bwd": round(total / 1e9, 1),
        "flash_linear_gbytes": round(linear / 1e9, 2),
        "measured": "xla_cost_analysis",
    })
    return total, linear


def decode_step_floor(batch=4):
    """Bandwidth floor for the generate north star: cost-analyze ONE
    cached decode step (loop-free) and multiply by the image length."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE, init_decode_cache

    model = DALLE(
        dim=DIM, depth=DEPTH, heads=HEADS, dim_head=DIM_HEAD,
        num_image_tokens=8192, image_fmap_size=FMAP,
        num_text_tokens=10000, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True, dtype=jnp.bfloat16,
    )
    text = jnp.ones((batch, TEXT_SEQ), jnp.int32)
    tokens = jnp.zeros((batch, FMAP * FMAP), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0), text, tokens)[
        "params"
    ]
    params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
    cache = init_decode_cache(model, batch)

    def step(params, tok, pos, cache):
        return model.apply(
            {"params": params}, tok, pos, cache,
            method=DALLE.decode_image_step,
        )

    compiled = jax.jit(step).lower(
        params, jnp.zeros((batch,), jnp.int32), jnp.zeros((), jnp.int32),
        cache,
    ).compile()
    nbytes = float(extract_cost(compiled).get("bytes accessed", 0.0))
    n_img = FMAP * FMAP
    floor_s = n_img * nbytes / V5E_HBM_BPS
    emit({
        "component": "cached_decode_step",
        "batch": batch,
        "gbytes_per_step": round(nbytes / 1e9, 2),
        "p50_bw_floor_s": round(floor_s, 2),
        "note": f"x{n_img} sequential steps; op-level bytes (overcounts "
                "fused traffic), params+cache re-read every step",
        "measured": "xla_cost_analysis",
    })


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    decode_step_floor()

    # loop-free compiled rows (forward_forward runs two inline applies)
    analyze("dense_remat_full", "forward_only", None)
    pol = analyze("dense_policy", "forward_only",
                  "dots_with_no_batch_dims_saveable")
    ff = analyze("ff_dense_policy", "forward_forward",
                 "dots_with_no_batch_dims_saveable")

    # measured flash lever: the dense score chain's op-level bytes per
    # layer (same metric as the rows above) collapse to linear traffic
    attn_total, attn_linear = measure_attention_chain()
    flash_delta = -DEPTH * (attn_total - attn_linear)
    # fused-CE lever: fwd + bwd fp32 [B, N, V] logits materializations
    # plus the softmax chain over them (~2 more passes), all -> chunked
    logits_fp32 = BATCH * SEQ * VOCAB * 4
    fused_delta = -4 * logits_fp32

    pol_flash = adjust(
        pol, "dense_policy+flash", flash_delta,
        "measured score-chain bytes -> flash linear traffic, x12 layers",
    )
    adjust(
        pol_flash, "policy+flash+fusedce", fused_delta,
        "also drop ~4 fp32 [B,N,V] logits passes (chunked CE)",
    )
    adjust(
        ff, "ff_policy+flash+2xfusedce",
        2 * flash_delta + 2 * fused_delta,
        "both objectives fused (round-4 inverse fused CE) + flash",
    )


if __name__ == "__main__":
    main()
