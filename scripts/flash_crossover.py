"""Dense↔flash crossover measurement: sets AUTO_FLASH_MIN_SEQ and
AUTO_FLASH_DECODE_MIN_LEN from data instead of folklore.

Methodology (the same hardware-free instrument as `scripts/hbm_model.py`,
whose r4 ladder the live TPU bench later validated): AOT-compile the REAL
dense attention program per sequence length on the CPU backend (same HLO
structure as TPU), read `compiled.cost_analysis()` FLOPs/bytes, and place
both kernels on the v5e roofline (197 TFLOP/s, ~819 GB/s):

  * dense: measured op-level bytes include the [B, H, N, N] fp32 score
    chain the fused MXU epilogue cannot eliminate once it spills VMEM;
  * flash: analytic tile traffic, EXACT from the kernel's BlockSpecs
    (q/o streamed once per q block; k/v once per LIVE (qi, ki) tile under
    the causal DMA skip — `_causal_last_live_k` is imported, not re-derived)
    plus the same measured matmul FLOPs halved by the causal block cut.

The prefill/training crossover is the first N where the dense program goes
BANDWIDTH-bound (bytes/BW > flops/peak): below it both kernels are
compute-bound and dense's tighter fusion wins (the r4 on-chip finding:
dense == fully-levered flash wall time at 1280 under dispatch overhead);
above it dense pays score traffic that flash simply does not have.

The decode crossover compares one cached step's K/V reads: dense always
reads the whole [B, H, max_len, D] cache; flash-decode reads
ceil(live/block_k) tiles (expected live ~ max_len/2 over an image) plus a
per-kernel overhead charge. Emits one JSON line per seq and a final
recommendation line. Caveats stated in BASELINE.md §flash-crossover; the
on-chip wall-clock A/B (`scripts/pallas_onchip.py`) stays armed in the
watchdog matrix as the final decider.

`--sparse` runs the BLOCK-SPARSE decode sweep instead (BASELINE.md
§block-sparse): for the flagship axial-row layout it reduces the static
pattern to per-row KV-tile bitmaps at several tile widths (the same
`ops/masks.py:mask_to_block_bitmap` reduction the serving policy ships at
runtime) and models, per width, the expected tiles read/skipped over a
full image decode plus the roofline step time with a per-tile grid charge.
The tension it quantifies: thin tiles skip more (a tile one live position
touches is read whole) but pay more grid steps; wide tiles amortise grid
overhead but smear the pattern. The sweep is what justifies
`DECODE_SPARSE_BLOCK = 128` in models/attention.py.

Usage: JAX_PLATFORMS=cpu python scripts/flash_crossover.py [--sparse]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# shared with the live serving-side accounting (obs/vitals.py:
# ProgramCostTable) so offline and live rooflines cannot drift
from dalle_pytorch_tpu.obs.vitals import (  # noqa: E402
    V5E_HBM_BPS, V5E_PEAK_FLOPS, extract_cost,
)
#: in-program Mosaic kernel overhead per pallas_call (grid setup; NOT a
#: host dispatch — the kernel runs inside the jitted step)
KERNEL_OVERHEAD_S = 5e-6

# serving/training flagship geometry (BASELINE.md): heads 16, head dim 64
BATCH, HEADS, DIM_HEAD = 4, 16, 64
BLOCK = 128
SEQS = (256, 384, 512, 640, 768, 1024, 1280, 1536, 2048, 4096)

# flagship text/image split: 256 text tokens + <bos>, fmap 32 -> 1024
# image tokens, decode cache max_len 1281
TEXT_SEQ, FMAP = 256, 32
#: per-grid-step charge inside the Mosaic kernel (DMA issue + bookkeeping
#: per (head, kv-tile) step) — the cost thin tiles multiply
TILE_STEP_OVERHEAD_S = 1e-7
SPARSE_BLOCKS = (32, 64, 128, 256, 512)


def measured_dense(seq, dtype):
    """cost_analysis FLOPs/bytes of the compiled dense causal attention."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_pytorch_tpu.ops.attention_core import dense_attention

    mask = jnp.asarray(np.tril(np.ones((seq, seq), dtype=bool))[None, None])
    q = jnp.zeros((BATCH, HEADS, seq, DIM_HEAD), dtype)

    compiled = (
        jax.jit(lambda q_, k_, v_: dense_attention(q_, k_, v_, mask=mask))
        .lower(q, q, q)
        .compile()
    )
    cost = extract_cost(compiled)
    return float(cost["flops"]), float(cost["bytes accessed"])


def flash_tile_bytes(seq, itemsize):
    """Exact causal-skip K/V tile traffic of the flash forward at this seq
    (q/o once per q block; k/v once per live (qi, ki) tile)."""
    from dalle_pytorch_tpu.ops.pallas_attention import _causal_last_live_k

    nq = -(-seq // BLOCK)
    live_tiles = sum(
        min(_causal_last_live_k(qi, BLOCK, BLOCK), nq - 1) + 1
        for qi in range(nq)
    )
    per_head = (
        2 * seq * DIM_HEAD  # q in, o out
        + 2 * live_tiles * BLOCK * DIM_HEAD  # k + v tiles
    ) * itemsize + seq * 4  # lse row, fp32
    return BATCH * HEADS * per_head


def decode_step_times(max_len, itemsize):
    """(dense_s, flash_s) roofline time of ONE cached decode step's
    attention reads at expected live length max_len/2 (bandwidth-bound:
    q is a single token)."""
    kv = 2 * BATCH * HEADS * max_len * DIM_HEAD * itemsize
    dense_s = kv / V5E_HBM_BPS
    live = max_len / 2
    tiles = -(-live // BLOCK)
    kv_flash = 2 * BATCH * HEADS * tiles * BLOCK * DIM_HEAD * itemsize
    flash_s = kv_flash / V5E_HBM_BPS + KERNEL_OVERHEAD_S
    return dense_s, flash_s


def sparse_sweep():
    """Tile-width sweep for the block-sparse flash-decode kernel.

    Pure host numpy over the REAL static layout (`_build_static_mask` +
    `mask_to_block_bitmap` — the exact reduction the serving policy ships),
    so the live/dead tile counts are the truth, not a model; only the time
    axis is a roofline. Per block width, averaged over every image decode
    position p (cache length text_len + p + 1):

      * tiles_read / tiles_skipped among causally in-range tiles — i.e.
        the policy's savings ON TOP of the PR 4 length skip, the same
        accounting as the fleet's kv_tiles_* counters;
      * roofline step time: live K/V tile bytes over HBM BW, plus the
        per-tile grid charge times in-range tiles (dead tiles still cost
        a grid step: the kernel skips their DMA and compute, not their
        index-map evaluation) and the per-kernel overhead.
    """
    import numpy as np

    from dalle_pytorch_tpu.models.transformer import _build_static_mask
    from dalle_pytorch_tpu.ops.masks import mask_to_block_bitmap

    itemsize = 2  # bf16 KV cache
    total = TEXT_SEQ + FMAP * FMAP
    max_len = total + 1
    text_len = TEXT_SEQ + 1
    image_seq = FMAP * FMAP
    mask = np.asarray(_build_static_mask("axial_row", total, FMAP, 0))
    if mask.shape[0] < max_len:
        pad = max_len - mask.shape[0]
        mask = np.pad(mask, ((0, pad), (0, pad)), constant_values=True)
    mask = mask[:max_len, :max_len]

    lens = text_len + np.arange(image_seq) + 1  # cache length at step p
    rows_out = []
    for blk in SPARSE_BLOCKS:
        nb = -(-max_len // blk)
        bitmap = mask_to_block_bitmap(
            mask, blk, n_blocks=nb, always_live=text_len
        )[text_len:][:image_seq]
        llb = (lens - 1) // blk
        in_range = np.arange(nb)[None, :] <= llb[:, None]
        live = bitmap & in_range
        read = live.sum(axis=1).astype(float)
        in_r = in_range.sum(axis=1).astype(float)
        live_frac = float(read.sum() / in_r.sum())
        # one decode step's K/V traffic (all heads; q is a single token)
        kv_read = 2 * BATCH * HEADS * read.mean() * blk * DIM_HEAD * itemsize
        kv_len = 2 * BATCH * HEADS * in_r.mean() * blk * DIM_HEAD * itemsize
        step_s = (
            kv_read / V5E_HBM_BPS
            + HEADS * in_r.mean() * TILE_STEP_OVERHEAD_S
            + KERNEL_OVERHEAD_S
        )
        len_skip_s = (
            kv_len / V5E_HBM_BPS
            + HEADS * in_r.mean() * TILE_STEP_OVERHEAD_S
            + KERNEL_OVERHEAD_S
        )
        rows_out.append(
            {
                "probe": "sparse_block_sweep",
                "pattern": "axial_row",
                "block": blk,
                "n_blocks": nb,
                "live_tile_frac": round(live_frac, 4),
                "tiles_read_mean": round(float(read.mean()), 2),
                "tiles_skipped_mean": round(float((in_r - read).mean()), 2),
                "kv_bytes_read_mean": int(kv_read),
                "kv_bytes_saved_mean": int(kv_len - kv_read),
                "decode_step_us": round(step_s * 1e6, 2),
                "decode_lengthskip_us": round(len_skip_s * 1e6, 2),
            }
        )
        print(json.dumps(rows_out[-1]), flush=True)
    best_saved = max(r["kv_bytes_saved_mean"] for r in rows_out)
    by_block = {r["block"]: r for r in rows_out}
    print(
        json.dumps(
            {
                "probe": "sparse_block_recommendation",
                "decode_sparse_block": 128,
                "savings_captured_vs_best": round(
                    by_block[128]["kv_bytes_saved_mean"] / best_saved, 4
                ),
                "basis": "128 matches flash_decode_attention's default "
                "block_k (all-ones bitmap keeps bit-identity with the "
                "dense-causal flash path) and sits at the roofline knee: "
                "thinner tiles save more bytes but the per-tile grid "
                "charge eats the win (32-wide models SLOWER than "
                "length-skip-only at 128); wider tiles smear the "
                "pattern and forfeit most of the skip",
            }
        ),
        flush=True,
    )


def main():
    import jax
    import jax.numpy as jnp

    dtype = jnp.bfloat16
    itemsize = 2
    prefill_cross = None
    decode_cross = None
    for seq in SEQS:
        flops, dense_bytes = measured_dense(seq, dtype)
        t_dense = max(flops / V5E_PEAK_FLOPS, dense_bytes / V5E_HBM_BPS)
        dense_bw_bound = dense_bytes / V5E_HBM_BPS > flops / V5E_PEAK_FLOPS
        fbytes = flash_tile_bytes(seq, itemsize)
        # causal block cut halves the matmul work; epilogue FLOPs are noise
        t_flash = max(
            (flops / 2) / V5E_PEAK_FLOPS, fbytes / V5E_HBM_BPS
        ) + KERNEL_OVERHEAD_S
        d_dense, d_flash = decode_step_times(seq, itemsize)
        row = {
            "probe": "flash_crossover",
            "seq": seq,
            "dense_flops": flops,
            "dense_bytes": dense_bytes,
            "flash_bytes": fbytes,
            "dense_roofline_us": round(t_dense * 1e6, 1),
            "flash_roofline_us": round(t_flash * 1e6, 1),
            "dense_bw_bound": dense_bw_bound,
            "decode_dense_us": round(d_dense * 1e6, 2),
            "decode_flash_us": round(d_flash * 1e6, 2),
            "device": jax.devices()[0].platform,
        }
        print(json.dumps(row), flush=True)
        if prefill_cross is None and dense_bw_bound and t_flash < t_dense:
            prefill_cross = seq
        if decode_cross is None and d_flash < d_dense:
            decode_cross = seq
    # Op-level counting cannot resolve the LOW end of the prefill bracket:
    # below ~1k tokens XLA's epilogue fusion may keep (part of) the score
    # chain out of HBM, so "dense is BW-bound from `prefill_cross` on" is a
    # lower bound, not a crossover. The r4 hardware anchor (flash == dense
    # wall at 1280 even under dispatch overhead; the r3 HBM analysis says
    # flash wins there outright) caps the bracket from above. Recommend the
    # largest bench-grid point that still auto-selects flash for the
    # flagship 1280: every estimate agrees there, and the unreliable
    # sub-1k region stays dense until the on-chip A/B rules on it.
    recommended_prefill = 1024
    print(
        json.dumps(
            {
                "probe": "flash_crossover_recommendation",
                "prefill_bracket_low_seq": prefill_cross,
                "prefill_hardware_anchor_seq": 1280,
                "auto_flash_min_seq": recommended_prefill,
                "auto_flash_decode_min_len": decode_cross,
                "basis": "v5e roofline over measured dense cost_analysis; "
                "on-chip wall-clock A/B remains the final decider",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if "--sparse" in sys.argv[1:]:
        sparse_sweep()
    else:
        main()
