"""Dense↔flash crossover measurement: sets AUTO_FLASH_MIN_SEQ and
AUTO_FLASH_DECODE_MIN_LEN from data instead of folklore.

Methodology (the same hardware-free instrument as `scripts/hbm_model.py`,
whose r4 ladder the live TPU bench later validated): AOT-compile the REAL
dense attention program per sequence length on the CPU backend (same HLO
structure as TPU), read `compiled.cost_analysis()` FLOPs/bytes, and place
both kernels on the v5e roofline (197 TFLOP/s, ~819 GB/s):

  * dense: measured op-level bytes include the [B, H, N, N] fp32 score
    chain the fused MXU epilogue cannot eliminate once it spills VMEM;
  * flash: analytic tile traffic, EXACT from the kernel's BlockSpecs
    (q/o streamed once per q block; k/v once per LIVE (qi, ki) tile under
    the causal DMA skip — `_causal_last_live_k` is imported, not re-derived)
    plus the same measured matmul FLOPs halved by the causal block cut.

The prefill/training crossover is the first N where the dense program goes
BANDWIDTH-bound (bytes/BW > flops/peak): below it both kernels are
compute-bound and dense's tighter fusion wins (the r4 on-chip finding:
dense == fully-levered flash wall time at 1280 under dispatch overhead);
above it dense pays score traffic that flash simply does not have.

The decode crossover compares one cached step's K/V reads: dense always
reads the whole [B, H, max_len, D] cache; flash-decode reads
ceil(live/block_k) tiles (expected live ~ max_len/2 over an image) plus a
per-kernel overhead charge. Emits one JSON line per seq and a final
recommendation line. Caveats stated in BASELINE.md §flash-crossover; the
on-chip wall-clock A/B (`scripts/pallas_onchip.py`) stays armed in the
watchdog matrix as the final decider.

Usage: JAX_PLATFORMS=cpu python scripts/flash_crossover.py
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# shared with the live serving-side accounting (obs/vitals.py:
# ProgramCostTable) so offline and live rooflines cannot drift
from dalle_pytorch_tpu.obs.vitals import (  # noqa: E402
    V5E_HBM_BPS, V5E_PEAK_FLOPS, extract_cost,
)
#: in-program Mosaic kernel overhead per pallas_call (grid setup; NOT a
#: host dispatch — the kernel runs inside the jitted step)
KERNEL_OVERHEAD_S = 5e-6

# serving/training flagship geometry (BASELINE.md): heads 16, head dim 64
BATCH, HEADS, DIM_HEAD = 4, 16, 64
BLOCK = 128
SEQS = (256, 384, 512, 640, 768, 1024, 1280, 1536, 2048, 4096)


def measured_dense(seq, dtype):
    """cost_analysis FLOPs/bytes of the compiled dense causal attention."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_pytorch_tpu.ops.attention_core import dense_attention

    mask = jnp.asarray(np.tril(np.ones((seq, seq), dtype=bool))[None, None])
    q = jnp.zeros((BATCH, HEADS, seq, DIM_HEAD), dtype)

    compiled = (
        jax.jit(lambda q_, k_, v_: dense_attention(q_, k_, v_, mask=mask))
        .lower(q, q, q)
        .compile()
    )
    cost = extract_cost(compiled)
    return float(cost["flops"]), float(cost["bytes accessed"])


def flash_tile_bytes(seq, itemsize):
    """Exact causal-skip K/V tile traffic of the flash forward at this seq
    (q/o once per q block; k/v once per live (qi, ki) tile)."""
    from dalle_pytorch_tpu.ops.pallas_attention import _causal_last_live_k

    nq = -(-seq // BLOCK)
    live_tiles = sum(
        min(_causal_last_live_k(qi, BLOCK, BLOCK), nq - 1) + 1
        for qi in range(nq)
    )
    per_head = (
        2 * seq * DIM_HEAD  # q in, o out
        + 2 * live_tiles * BLOCK * DIM_HEAD  # k + v tiles
    ) * itemsize + seq * 4  # lse row, fp32
    return BATCH * HEADS * per_head


def decode_step_times(max_len, itemsize):
    """(dense_s, flash_s) roofline time of ONE cached decode step's
    attention reads at expected live length max_len/2 (bandwidth-bound:
    q is a single token)."""
    kv = 2 * BATCH * HEADS * max_len * DIM_HEAD * itemsize
    dense_s = kv / V5E_HBM_BPS
    live = max_len / 2
    tiles = -(-live // BLOCK)
    kv_flash = 2 * BATCH * HEADS * tiles * BLOCK * DIM_HEAD * itemsize
    flash_s = kv_flash / V5E_HBM_BPS + KERNEL_OVERHEAD_S
    return dense_s, flash_s


def main():
    import jax
    import jax.numpy as jnp

    dtype = jnp.bfloat16
    itemsize = 2
    prefill_cross = None
    decode_cross = None
    for seq in SEQS:
        flops, dense_bytes = measured_dense(seq, dtype)
        t_dense = max(flops / V5E_PEAK_FLOPS, dense_bytes / V5E_HBM_BPS)
        dense_bw_bound = dense_bytes / V5E_HBM_BPS > flops / V5E_PEAK_FLOPS
        fbytes = flash_tile_bytes(seq, itemsize)
        # causal block cut halves the matmul work; epilogue FLOPs are noise
        t_flash = max(
            (flops / 2) / V5E_PEAK_FLOPS, fbytes / V5E_HBM_BPS
        ) + KERNEL_OVERHEAD_S
        d_dense, d_flash = decode_step_times(seq, itemsize)
        row = {
            "probe": "flash_crossover",
            "seq": seq,
            "dense_flops": flops,
            "dense_bytes": dense_bytes,
            "flash_bytes": fbytes,
            "dense_roofline_us": round(t_dense * 1e6, 1),
            "flash_roofline_us": round(t_flash * 1e6, 1),
            "dense_bw_bound": dense_bw_bound,
            "decode_dense_us": round(d_dense * 1e6, 2),
            "decode_flash_us": round(d_flash * 1e6, 2),
            "device": jax.devices()[0].platform,
        }
        print(json.dumps(row), flush=True)
        if prefill_cross is None and dense_bw_bound and t_flash < t_dense:
            prefill_cross = seq
        if decode_cross is None and d_flash < d_dense:
            decode_cross = seq
    # Op-level counting cannot resolve the LOW end of the prefill bracket:
    # below ~1k tokens XLA's epilogue fusion may keep (part of) the score
    # chain out of HBM, so "dense is BW-bound from `prefill_cross` on" is a
    # lower bound, not a crossover. The r4 hardware anchor (flash == dense
    # wall at 1280 even under dispatch overhead; the r3 HBM analysis says
    # flash wins there outright) caps the bracket from above. Recommend the
    # largest bench-grid point that still auto-selects flash for the
    # flagship 1280: every estimate agrees there, and the unreliable
    # sub-1k region stays dense until the on-chip A/B rules on it.
    recommended_prefill = 1024
    print(
        json.dumps(
            {
                "probe": "flash_crossover_recommendation",
                "prefill_bracket_low_seq": prefill_cross,
                "prefill_hardware_anchor_seq": 1280,
                "auto_flash_min_seq": recommended_prefill,
                "auto_flash_decode_min_len": decode_cross,
                "basis": "v5e roofline over measured dense cost_analysis; "
                "on-chip wall-clock A/B remains the final decider",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
