#!/usr/bin/env python
"""Analytic NECESSARY-HBM-traffic model for the flagship train step.

VERDICT r4 #3 asks for a measured roofline from the banked 7.7% MFU to
the >=45% target — or a quantitative refutation. The hardware half (the
probe ladder) is armed in the watchdog matrix; this script supplies the
model half: a lower-bound estimate of the HBM bytes a WELL-FUSED XLA
program must move per step, as opposed to `cost_analysis()`'s op-level
operand counting (which charges every elementwise op its full operands
— 886 GB/step at the same levers (flash+policy+fused CE); 1.34 TB for
the dense full-remat baseline — and therefore wildly overcounts what
the fused program actually streams).

Counting rules (bf16 activations/params, fp32 master adds x2 where
noted):
  * every tensor the autodiff must SAVE (remat policy
    dots_with_no_batch_dims_saveable: matmul outputs) is written once in
    the forward and read once in the backward;
  * the residual stream is read+written once per block per direction
    (fused with the adjacent matmuls beyond that);
  * flash attention streams Q/K/V/O once per pass plus the saved lse —
    score tensors never touch HBM (that is the point of flash; the
    causal DMA-skip removes the dead-tile re-reads);
  * fused CE streams the hidden states and the head weight once per
    chunk pass (logits are never materialized);
  * params: read fwd + read bwd + grad write + Adam moments read/write
    (fp32) + fp32 master read/write.

The result is a LOWER bound (perfect fusion, no spills); the true
program sits between this and the op-level count. Prints one JSON line
and a small table.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

# flagship geometry + v5e roofline constants: one source of truth with
# the op-level model (hbm_model re-exports the roofline anchors from
# dalle_pytorch_tpu.obs.vitals — the same numbers the live serving MFU
# gauges use; importing it pulls jax, but backends initialize lazily so
# this stays side-effect-free)
from hbm_model import (  # noqa: E402
    BATCH, DEPTH, DIM, DIM_HEAD, HEADS, SEQ, V5E_HBM_BPS, V5E_PEAK_FLOPS,
    VOCAB,
)

B, S, D, L = BATCH, SEQ, DIM, DEPTH
DH = DIM_HEAD
V = VOCAB
FF_MULT = 4
BF16, F32 = 2, 4

GB = 1e9


def gb(x):
    return x / GB


def main():
    bsd = B * S * D * BF16

    # ---- per-layer saved activations (dots policy: matmul outputs) ----
    qkv_out = 3 * bsd            # to_qkv output
    attn_o = bsd                 # flash O (saved for backward)
    lse = B * HEADS * S * 1 * F32
    attn_proj = bsd              # out-projection output
    ff_in = 2 * FF_MULT * bsd    # GEGLU up-projection (2 branches)
    ff_out = bsd                 # down-projection output
    saved_per_layer = qkv_out + attn_o + lse + attn_proj + ff_in + ff_out
    # each saved tensor: 1 write (fwd) + 1 read (bwd)
    saved_traffic = 2 * saved_per_layer * L

    # ---- flash attention streaming (fwd + dq + dkv passes) ----
    # per pass Q, K, V each read once; O written (fwd) / dO read + dq/dkv
    # written (bwd). 3 passes stream ~4 x [B,H,S,DH] tensors each.
    bhsd = B * HEADS * S * DH * BF16
    flash_traffic = L * (4 * bhsd + 2 * (4 * bhsd))

    # ---- residual stream (read + write per block per direction) ----
    resid_traffic = L * 2 * (2 * bsd) * 2  # 2 blocks/layer, fwd+bwd

    # ---- embeddings + logits head (fused CE, chunked) ----
    emb_traffic = 2 * bsd  # token+pos gather out fwd, grad scatter bwd
    head_w = D * V * BF16
    # fwd chunk pass + recompute in bwd + dW grad write + dh read/write
    ce_traffic = 2 * (bsd + head_w) + head_w * 2 + 2 * bsd

    # ---- params + optimizer ----
    n_params = (
        L * (3 * D * D + D * D + 2 * FF_MULT * D * D + FF_MULT * D * D)
        + V * D + D * V
    )
    p_bf16 = n_params * BF16
    p_f32 = n_params * F32
    #   read fwd + read bwd (recompute streams) + grad write (fp32)
    # + adam m,v read+write (fp32) + master read+write (fp32)
    param_traffic = 2 * p_bf16 + p_f32 + 4 * p_f32 + 2 * p_f32

    total = (
        saved_traffic + flash_traffic + resid_traffic
        + emb_traffic + ce_traffic + param_traffic
    )

    # device-time model (33.1e12 = the policy-remat step FLOPs measured
    # by hbm_model.py's cost-analysis table, round 4)
    flops = 33.1e12
    t_mxu = flops / V5E_PEAK_FLOPS
    t_hbm = total / V5E_HBM_BPS
    bound = max(t_mxu, t_hbm)
    mfu_ceiling = t_mxu / bound

    rows = [
        ("saved activations (dots policy) x12", saved_traffic),
        ("flash Q/K/V/O streams x12 (3 passes)", flash_traffic),
        ("residual stream x12", resid_traffic),
        ("embeddings", emb_traffic),
        ("fused-CE head (chunked)", ce_traffic),
        ("params + Adam (fp32 moments/master)", param_traffic),
    ]
    print(f"{'component':44s} {'GB/step':>8s}")
    for name, b in rows:
        print(f"{name:44s} {gb(b):8.1f}")
    print(f"{'TOTAL necessary (lower bound)':44s} {gb(total):8.1f}")
    print()
    print(
        f"t_mxu {t_mxu*1e3:.0f} ms vs t_hbm {t_hbm*1e3:.0f} ms -> "
        f"{'COMPUTE' if t_mxu >= t_hbm else 'BANDWIDTH'}-bound; "
        f"MFU ceiling {mfu_ceiling*100:.0f}%"
    )
    print(json.dumps({
        "metric": "necessary_bytes_model",
        "value": round(gb(total), 1),
        "unit": "GB/step",
        "vs_baseline": None,
        "t_mxu_ms": round(t_mxu * 1e3, 1),
        "t_hbm_ms": round(t_hbm * 1e3, 1),
        "mfu_ceiling": round(mfu_ceiling, 3),
        "oplevel_gb": 886,  # hbm_model.py op-level count for contrast
    }))


if __name__ == "__main__":
    main()
