#!/usr/bin/env python
"""End-to-end rainbow example: dVAE -> DALLE -> exact token accuracy.

Script equivalent of the reference's `examples/rainbow_dalle.ipynb` (the
de-facto integration test of the reference, SURVEY.md §4): render a
synthetic dataset of colored shapes with compositional captions, train the
DiscreteVAE, inspect reconstructions, train DALLE on a train split, and
measure exact image-token-sequence accuracy on train vs. held-out captions
(the notebook reports 1.0 train / ~0.3 test at convergence; reach it by
raising --vae-steps/--dalle-steps). Like the notebook's 9,216-variation
cross-product, the dataset is caption-unique up to 9,216 samples — each
caption determines its image exactly, which is what makes exact-match 1.0
reachable. Past that count combos repeat with un-captioned jitter and
per-token accuracy becomes the cleaner signal.

Run (CPU ok for small settings):
  python examples/rainbow_dalle.py --num-samples 512 --dalle-steps 300
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time
from pathlib import Path

import numpy as np

# runnable as `python examples/rainbow_dalle.py` without installing
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-samples", type=int, default=512)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--train-frac", type=float, default=0.7)
    p.add_argument("--vae-steps", type=int, default=300)
    p.add_argument("--dalle-steps", type=int, default=300)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--eval-samples", type=int, default=16)
    p.add_argument("--out-dir", type=str, default="rainbow_out")
    p.add_argument(
        "--steps-per-dispatch", type=int, default=1,
        help="optimizer steps scanned into one device dispatch for BOTH "
        "training loops (make_multi_step). Essential on synchronous-"
        "dispatch backends: at ~2s per dispatch round trip the 5500-step "
        "notebook-scale run cannot finish per-step, but 16 steps/dispatch "
        "brings it to minutes",
    )
    p.add_argument("--cpu", action="store_true", help="force CPU platform")
    return p.parse_args()


def main():
    args = parse_args()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from dalle_pytorch_tpu.data.rainbow import RainbowDataset
    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.models.dalle import DALLE, generate_images_cached
    from dalle_pytorch_tpu.training.steps import (
        TrainState, make_optimizer, make_vae_train_step, make_dalle_train_step,
        make_multi_step, stack_batches, window_iter, window_keys,
    )
    from dalle_pytorch_tpu.utils.images import save_image_grid

    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    tokenizer = ByteTokenizer()
    # captions run up to 54 bytes ("small outline striped magenta rectangle
    # rotated thrice"); 64 keeps every caption un-truncated — truncation
    # would collapse distinct captions onto identical token sequences and
    # silently cap exact-match below 1.0
    text_seq_len = 64

    data = RainbowDataset(num_samples=args.num_samples, image_size=args.image_size)
    n_train = int(len(data) * args.train_frac)
    print(f"{len(data)} samples ({n_train} train), e.g. {data.caption(0)!r}")

    # ---------------------------------------------------------------- dVAE
    vae = DiscreteVAE(
        image_size=args.image_size, num_layers=2, num_tokens=256,
        codebook_dim=128, hidden_dim=64,
    )
    imgs0 = np.stack([data.image(i) for i in range(args.batch_size)])
    vparams = jax.jit(vae.init)(jax.random.PRNGKey(0), imgs0)["params"]
    vstate = TrainState.create(
        apply_fn=vae.apply, params=vparams, tx=make_optimizer(3e-4)
    )
    vstep = jax.jit(make_vae_train_step(vae))
    spd = max(1, args.steps_per_dispatch)
    vstep_multi = (
        jax.jit(make_multi_step(make_vae_train_step(vae), spd)) if spd > 1 else None
    )

    def vae_stream():
        epoch = 0
        while True:
            for b in data.batches(args.batch_size, tokenizer, text_seq_len,
                                  shuffle_seed=epoch):
                yield b
            epoch += 1

    # fold_in(step) keys, as make_multi_step prescribes: the random stream
    # is a pure function of the step index, so it is invariant to
    # --steps-per-dispatch. The temperature anneal below is applied at
    # window granularity (full-window decay up front), so temp can differ
    # from a per-step run by up to spd-1 decay factors mid-window
    vae_rng = jax.random.PRNGKey(1)
    t0, step = time.time(), 0
    temp = 1.0
    for win in window_iter(
        itertools.islice(vae_stream(), args.vae_steps), spd
    ):
        prev = step
        keys = window_keys(vae_rng, step, len(win))
        if vstep_multi is not None and len(win) == spd:
            # per-window anneal: the product of n per-step decays applied
            # up front (`train_vae.py:278` semantics at window granularity)
            temp = max(temp * float(np.exp(-1e-3 * len(win))), 0.5)
            vstate, m = vstep_multi(
                vstate,
                jnp.asarray(stack_batches([b["images"] for b in win])),
                keys, jnp.float32(temp),
            )
            step += len(win)
        else:
            for b, r in zip(win, keys):
                # gumbel temperature annealing (`train_vae.py:278` semantics)
                temp = max(temp * np.exp(-1e-3), 0.5)
                vstate, m = vstep(vstate, jnp.asarray(b["images"]), r,
                                  jnp.float32(temp))
                step += 1
        if step // 100 > prev // 100:
            print(f"vae step {step}: loss {float(m['loss']):.4f}")
    print(f"dVAE trained in {time.time()-t0:.0f}s")

    # hard reconstructions (codebook roundtrip), like notebook cells 20-22
    toks = vae.apply({"params": vstate.params}, imgs0,
                     method=DiscreteVAE.get_codebook_indices)
    recon = vae.apply({"params": vstate.params}, toks, method=DiscreteVAE.decode)

    # the decoder works in normalized space (its loss targets norm(img));
    # denormalize to image space before comparing / saving
    means = np.asarray(vae.normalization[0][:3])
    stds = np.asarray(vae.normalization[1][:3])
    denorm = lambda x: np.asarray(x) * stds + means
    mse = float(np.mean((denorm(recon) - imgs0) ** 2))
    print(f"hard-recon MSE: {mse:.4f}; codebook usage: "
          f"{len(np.unique(np.asarray(toks)))}/{vae.num_tokens}")
    save_image_grid(denorm(recon), out_dir / "recon.png")

    # --------------------------------------------------------------- DALLE
    fmap = vae.fmap_size
    model = DALLE(
        dim=128, depth=4, heads=4, dim_head=32,
        num_image_tokens=vae.num_tokens, image_fmap_size=fmap,
        num_text_tokens=tokenizer.vocab_size, text_seq_len=text_seq_len,
        shift_tokens=True, rotary_emb=True,
    )
    text0 = jnp.asarray(tokenizer.tokenize(
        [data.caption(i) for i in range(2)], text_seq_len, truncate_text=True))
    dparams = jax.jit(model.init)(jax.random.PRNGKey(2), text0, toks[:2])["params"]
    dstate = TrainState.create(
        apply_fn=model.apply, params=dparams,
        tx=make_optimizer(3e-4, clip_grad_norm=0.5),
    )
    dstep = jax.jit(make_dalle_train_step(model, vae=vae))
    dstep_multi = (
        jax.jit(make_multi_step(make_dalle_train_step(model, vae=vae), spd))
        if spd > 1 else None
    )

    def dalle_batch(step):
        # draw minibatches from the train split only; the tail of the
        # dataset stays held out for the accuracy bar below
        sel = np.random.RandomState(step).choice(
            n_train, size=min(args.batch_size, n_train), replace=False
        )
        return {
            "text": np.asarray(tokenizer.tokenize(
                [data.caption(int(i)) for i in sel], text_seq_len,
                truncate_text=True)),
            "images": np.stack([data.image(int(i)) for i in sel]),
        }

    t0 = time.time()
    dalle_rng = jax.random.PRNGKey(3)
    step = 0
    for win in window_iter(
        (dalle_batch(s) for s in range(1, args.dalle_steps + 1)), spd
    ):
        prev = step
        keys = window_keys(dalle_rng, step, len(win))
        if dstep_multi is not None and len(win) == spd:
            stacked = stack_batches(win)
            dstate, m = dstep_multi(
                dstate,
                {k: jnp.asarray(v) for k, v in stacked.items()},
                keys, vstate.params,
            )
            step += len(win)
        else:
            for batch, r in zip(win, keys):
                dstate, m = dstep(
                    dstate,
                    {k: jnp.asarray(v) for k, v in batch.items()}, r,
                    vstate.params,
                )
                step += 1
        if step // 100 > prev // 100:
            print(f"dalle step {step}: loss {float(m['loss']):.4f}")
    print(f"DALLE trained in {time.time()-t0:.0f}s")

    # ------------------------- exact token accuracy (notebook cells 43-44)
    def exact_accuracy(indices):
        texts = [data.caption(i) for i in indices]
        gt_imgs = np.stack([data.image(i) for i in indices])
        gt = np.asarray(vae.apply({"params": vstate.params}, gt_imgs,
                                  method=DiscreteVAE.get_codebook_indices))
        ids = jnp.asarray(tokenizer.tokenize(texts, text_seq_len,
                                             truncate_text=True))
        # near-greedy sampling for determinism
        sampled = generate_images_cached(
            model, {"params": dstate.params}, jax.random.PRNGKey(9), ids,
            temperature=1e-4, filter_thres=0.999,
        )
        sampled = np.asarray(sampled)
        exact = float((sampled == gt).all(axis=1).mean())
        per_tok = float((sampled == gt).mean())
        return exact, per_tok, sampled

    train_idx = list(range(min(args.eval_samples, n_train)))
    test_idx = list(range(n_train, min(n_train + args.eval_samples, len(data))))
    tr_exact, tr_tok, sampled = exact_accuracy(train_idx)
    report = f"train: exact {tr_exact:.2f}, per-token {tr_tok:.3f}"
    te_exact = te_tok = None
    if test_idx:
        te_exact, te_tok, _ = exact_accuracy(test_idx)
        report += f" | test: exact {te_exact:.2f}, per-token {te_tok:.3f}"
    print(report)
    print("(reference notebook bar at convergence: exact 1.0 train / ~0.3 test)")
    # machine-readable line for the TPU experiment matrix
    # (scripts/run_tpu_experiments.sh greps '^{')
    import json

    print(json.dumps({
        "metric": "rainbow_convergence",
        "num_samples": len(data),
        "dalle_steps": args.dalle_steps,
        "train_exact": round(tr_exact, 4),
        "train_per_token": round(tr_tok, 4),
        "test_exact": None if te_exact is None else round(te_exact, 4),
        "test_per_token": None if te_tok is None else round(te_tok, 4),
        "device": jax.devices()[0].device_kind,
        "notebook_bar": "train exact 1.0 / test ~0.3",
    }))

    gen = vae.apply({"params": vstate.params}, jnp.asarray(sampled),
                    method=DiscreteVAE.decode)
    save_image_grid(denorm(gen), out_dir / "generated.png")
    print(f"wrote {out_dir}/recon.png and {out_dir}/generated.png")


if __name__ == "__main__":
    main()
