"""Benchmark: DALLE training throughput (image-tokens/sec/chip) + MFU.

Runs the flagship train step (dim 1024 / depth 12, OpenAI-dVAE geometry:
256 text + 1024 image tokens, bf16 compute) on the available accelerator
and prints ONE JSON line. The reference publishes no numbers (BASELINE.md) — its only runtime
metric is `sample_per_sec` (`/root/reference/train_dalle.py:578-581`) — so
`vs_baseline` is reported against the ≥45%-MFU design target from
BASELINE.json (value 1.0 == exactly hitting the target scaled to this
chip count).
"""

from __future__ import annotations

import json
import sys
import time

METRIC = "dalle_train_image_tokens_per_sec_per_chip"
UNIT = "img-tok/s/chip"


# published bf16 peak FLOP/s per chip
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,  # v5p
    "v6": 918e12,
    "cpu": 5e11,  # nominal, so CPU runs still report something
}


def peak_flops_per_chip() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def transformer_train_flops(dim, depth, heads, dim_head, seq, ff_mult=4) -> float:
    """Analytic fwd+bwd matmul FLOPs per sample for one step."""
    inner = heads * dim_head
    per_layer = (
        2 * seq * dim * 3 * inner          # qkv proj
        + 2 * seq * seq * inner * 2        # qk^T and attn@v
        + 2 * seq * inner * dim            # out proj
        + 2 * seq * dim * dim * ff_mult * 2  # ff up (GEGLU: 2x width)
        + 2 * seq * dim * ff_mult * dim    # ff down
    )
    fwd = depth * per_layer
    return 3 * fwd  # fwd + 2x bwd


def main():
    import os

    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.training import TrainState, make_optimizer, make_dalle_train_step

    # BASELINE.json ladder config: DALLE dim=1024 depth=12 with OpenAI-dVAE
    # geometry (f/8: 32x32 = 1024 image tokens, seq 1280). Env overrides for
    # A/B runs: BENCH_BATCH, BENCH_FMAP, BENCH_ATTN (dense|flash|auto).
    dim, depth, heads, dim_head = 1024, 12, 16, 64
    text_seq = 256
    fmap = int(os.environ.get("BENCH_FMAP", "32"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))
    attn_impl = os.environ.get("BENCH_ATTN", "auto")
    image_seq = fmap * fmap
    seq = text_seq + image_seq

    model = DALLE(
        dim=dim, depth=depth, heads=heads, dim_head=dim_head,
        num_image_tokens=8192, image_fmap_size=fmap,
        num_text_tokens=10000, text_seq_len=text_seq,
        shift_tokens=True, rotary_emb=True, attn_impl=attn_impl,
        dtype=jnp.bfloat16,
    )
    text = jnp.ones((batch, text_seq), jnp.int32)
    tokens = jnp.zeros((batch, image_seq), jnp.int32)
    # jit the init: eager init dispatches each op separately, which is
    # painfully slow on remote/tunneled devices
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(3e-4, clip_grad_norm=0.5),
    )
    step = jax.jit(make_dalle_train_step(model), donate_argnums=0)
    batch_dict = {"text": text, "image_tokens": tokens}
    rng = jax.random.PRNGKey(1)

    # warmup / compile
    state, metrics = step(state, batch_dict, rng)
    jax.block_until_ready(metrics["loss"])

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    t0 = time.perf_counter()
    for i in range(n_steps):
        rng, r = jax.random.split(rng)
        state, metrics = step(state, batch_dict, r)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    steps_per_sec = n_steps / dt
    img_tok_per_sec_chip = steps_per_sec * batch * image_seq / n_chips
    flops_per_step = transformer_train_flops(dim, depth, heads, dim_head, seq) * batch
    mfu = flops_per_step * steps_per_sec / (peak_flops_per_chip() * n_chips)

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(img_tok_per_sec_chip, 1),
                "unit": UNIT,
                "ok": True,
                "vs_baseline": round(mfu / 0.45, 4),
                "mfu": round(mfu, 4),
                "samples_per_sec": round(steps_per_sec * batch, 2),
                "device": jax.devices()[0].device_kind,
                "n_chips": n_chips,
                "config": f"dim{dim}-depth{depth}-seq{seq}-bs{batch}-{attn_impl}-bf16",
            }
        )
    )


if __name__ == "__main__":
    if "--child" in sys.argv:
        main()
    else:
        from bench_common import run_guarded

        run_guarded(
            METRIC,
            UNIT,
            __file__,
            child_timeout=1800.0,
            # CPU fallback: shrink to something that finishes, still a
            # valid (clearly-labelled) record rather than a dead signal.
            cpu_env_defaults={
                "BENCH_BATCH": "1",
                "BENCH_FMAP": "16",
                "BENCH_STEPS": "3",
            },
        )
