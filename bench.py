"""Benchmark: DALLE training throughput (image-tokens/sec/chip) + MFU.

Runs the flagship train step (dim 1024 / depth 12, OpenAI-dVAE geometry:
256 text + 1024 image tokens, bf16 compute) on the available accelerator
and prints ONE JSON line. The reference publishes no numbers (BASELINE.md) — its only runtime
metric is `sample_per_sec` (`/root/reference/train_dalle.py:578-581`) — so
`vs_baseline` is reported against the ≥45%-MFU design target from
BASELINE.json (value 1.0 == exactly hitting the target scaled to this
chip count).
"""

from __future__ import annotations

import json
import os
import sys
import time

METRIC = "dalle_train_image_tokens_per_sec_per_chip"
UNIT = "img-tok/s/chip"


# FLOPs/peak accounting lives in dalle_pytorch_tpu.utils.flops; imported
# lazily so the guard parent process stays light (no jax/flax import
# before forking the child).


def peak_flops_per_chip() -> float:
    import jax

    from dalle_pytorch_tpu.utils.flops import peak_flops_per_chip as _peak

    return _peak(jax.devices()[0].device_kind)


def main():
    import jax

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.training import (
        TrainState,
        make_dalle_train_step,
        make_multi_step,
        make_optimizer,
    )
    from dalle_pytorch_tpu.utils.flops import transformer_train_flops

    # BASELINE.json ladder config: DALLE dim=1024 depth=12 with OpenAI-dVAE
    # geometry (f/8: 32x32 = 1024 image tokens, seq 1280). Env overrides for
    # A/B runs: BENCH_BATCH, BENCH_FMAP, BENCH_ATTN (dense|flash|auto),
    # BENCH_REMAT (per-layer rematerialization; without it the bf16
    # [B,1280,4096] GEGLU activations of all 12 layers stay live through the
    # backward and batch 16 blows 16G HBM — the round-2 failure mode),
    # BENCH_ACCUM (gradient accumulation: global batch stays BENCH_BATCH,
    # split into BENCH_ACCUM scanned microbatches).
    dim, depth, heads, dim_head = 1024, 12, 16, 64
    text_seq = 256
    fmap = int(os.environ.get("BENCH_FMAP", "32"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))
    accum = int(os.environ.get("BENCH_ACCUM", "1"))
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    # jax.checkpoint policy for the remat executor; "none" = full recompute
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "none")
    remat_policy = None if remat_policy == "none" else remat_policy
    attn_impl = os.environ.get("BENCH_ATTN", "auto")
    # comma list, e.g. "full,axial_row,axial_col,conv_like" — cycled over
    # layers like the reference's attn_types; masked types run dense with
    # per-layer pattern masks (scan executor scans them over depth)
    attn_types = os.environ.get("BENCH_ATTN_TYPES")
    attn_types = tuple(attn_types.split(",")) if attn_types else None
    fused_ce = os.environ.get("BENCH_FUSED_CE", "0") == "1"
    # "scan" compiles ONE layer body instead of `depth` copies — ~12x
    # smaller program; the tunneled backend has died mid-compile on the
    # unrolled flagship repeatedly, so small compiles are also robustness
    executor = os.environ.get("BENCH_EXECUTOR", "unrolled")
    # BENCH_SCAN_STEPS=S runs S optimizer steps per dispatch via
    # make_multi_step (host-loop elimination): on synchronous-dispatch
    # backends (the tunneled TPU) each jitted call pays a full round
    # trip, which bounds steps/sec regardless of program speed; scanning
    # amortizes one round trip over S real steps.
    scan_steps = int(os.environ.get("BENCH_SCAN_STEPS", "1"))
    image_seq = fmap * fmap
    seq = text_seq + image_seq

    model = DALLE(
        dim=dim, depth=depth, heads=heads, dim_head=dim_head,
        num_image_tokens=8192, image_fmap_size=fmap,
        num_text_tokens=10000, text_seq_len=text_seq,
        shift_tokens=True, rotary_emb=True, attn_impl=attn_impl,
        attn_types=attn_types,
        reversible=remat, reversible_impl="remat", remat_policy=remat_policy,
        fused_ce=fused_ce, executor=executor,
        dtype=jnp.bfloat16,
    )
    text = jnp.ones((batch, text_seq), jnp.int32)
    tokens = jnp.zeros((batch, image_seq), jnp.int32)
    # jit the init: eager init dispatches each op separately, which is
    # painfully slow on remote/tunneled devices
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(3e-4, clip_grad_norm=0.5),
    )
    step_fn = make_dalle_train_step(model, grad_accum=accum)
    if scan_steps > 1:
        step = jax.jit(make_multi_step(step_fn, scan_steps), donate_argnums=0)
    else:
        step = jax.jit(step_fn, donate_argnums=0)
    batch_dict = {"text": text, "image_tokens": tokens}
    if scan_steps > 1:
        # token ids only — the [S, B, seq] int32 window is ~a few MB
        batch_dict = jax.tree.map(
            lambda x: jnp.repeat(x[None], scan_steps, 0), batch_dict
        )
    rng = jax.random.PRNGKey(1)

    def call(state, b, r):
        if scan_steps > 1:
            return step(state, b, jax.random.split(r, scan_steps))
        return step(state, b, r)

    # warmup / compile (float() forces completion; see timing note below)
    state, metrics = call(state, batch_dict, rng)
    float(metrics["loss"])

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    # keep the dispatch count whole; the metric divides by the true count
    n_dispatches = max(1, n_steps // scan_steps)
    n_steps = n_dispatches * scan_steps
    # BENCH_INPUT=host: feed every step through the real input machinery —
    # per-step host batch assembly (numpy tokenize-shaped work + device_put)
    # overlapped via the Prefetcher — and report the measured input-bound
    # fraction alongside throughput (VERDICT r2 missing #5 evidence).
    input_mode = os.environ.get("BENCH_INPUT", "synthetic")
    prefetcher = None
    if input_mode == "host":
        import numpy as np

        from dalle_pytorch_tpu.data.prefetch import Prefetcher

        host_rng = np.random.RandomState(0)

        def host_batches():
            # batch GENERATION stays inside the pipeline so the measured
            # wait fraction includes real host-side assembly work, not just
            # the transfer; with multi-stepping one yielded item is a whole
            # [scan_steps, ...] window (one transfer per dispatch)
            for _ in range(n_dispatches):
                window = [
                    {
                        "text": host_rng.randint(1, 9000, (batch, text_seq)),
                        "image_tokens": host_rng.randint(
                            0, 8192, (batch, image_seq)
                        ),
                    }
                    for _ in range(scan_steps)
                ]
                yield window if scan_steps > 1 else window[0]

        def assemble(b):
            if scan_steps > 1:
                from dalle_pytorch_tpu.training import stack_batches

                b = stack_batches(b)
            return {
                "text": jax.device_put(b["text"].astype(np.int32)),
                "image_tokens": jax.device_put(b["image_tokens"].astype(np.int32)),
            }

        prefetcher = Prefetcher(host_batches(), transform=assemble, depth=2)

    t0 = time.perf_counter()
    done_steps = 0
    if prefetcher is not None:
        for dev_batch in prefetcher:
            rng, r = jax.random.split(rng)
            state, metrics = call(state, dev_batch, r)
            done_steps += scan_steps
        assert done_steps == n_steps, (done_steps, n_steps)
    else:
        for _ in range(n_dispatches):
            rng, r = jax.random.split(rng)
            state, metrics = call(state, batch_dict, r)
    # force completion with a value readback: block_until_ready is a no-op
    # on some tunneled backends, which would time dispatch instead of compute
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    platform = jax.devices()[0].platform
    is_fallback = platform == "cpu"
    steps_per_sec = n_steps / dt
    img_tok_per_sec_chip = steps_per_sec * batch * image_seq / n_chips
    vocab = model.total_tokens  # logits width; keeps the FLOPs numerator in sync
    flops_per_step = transformer_train_flops(
        dim, depth, heads, dim_head, seq, vocab=vocab
    ) * batch
    mfu = flops_per_step * steps_per_sec / (peak_flops_per_chip() * n_chips)

    out = {
        "metric": METRIC,
        "value": round(img_tok_per_sec_chip, 1),
        "unit": UNIT,
        "ok": True,
        # vs_baseline only means something against a real chip's peak;
        # CPU runs are smoke signals, not perf data (VERDICT r2 weak #7).
        "vs_baseline": None if is_fallback else round(mfu / 0.45, 4),
        "mfu": None if is_fallback else round(mfu, 4),
        "samples_per_sec": round(steps_per_sec * batch, 2),
        "device": jax.devices()[0].device_kind,
        "n_chips": n_chips,
        "config": (
            f"dim{dim}-depth{depth}-seq{seq}-gbs{batch}-accum{accum}-{attn_impl}"
            f"{'-types=' + ','.join(attn_types) if attn_types else ''}"
            f"-remat{int(remat)}{'-' + remat_policy if remat_policy else ''}"
            f"{'-fusedce' if fused_ce else ''}"
            f"{'-scan' if executor == 'scan' else ''}"
            f"{'-steps' + str(scan_steps) if scan_steps > 1 else ''}-bf16"
        ),
    }
    if prefetcher is not None:
        out["input_mode"] = "host"
        out["input_wait_frac"] = round(prefetcher.wait_fraction, 4)
    if is_fallback:
        out["fallback"] = True
    print(json.dumps(out))


def _microbatch_of(env) -> "int | None":
    """Live microbatch implied by an env dict; None when invalid (accum
    must evenly divide the global batch for `_microbatch`'s reshape)."""
    try:
        b = int(env.get("BENCH_BATCH", "16"))
        a = int(env.get("BENCH_ACCUM", "1"))
    except ValueError:
        return None
    if a <= 0 or b <= 0 or b % a:
        return None
    return b // a


if __name__ == "__main__":
    from bench_common import ensure_compile_cache

    ensure_compile_cache()
    if "--child" in sys.argv:
        main()
    else:
        from bench_common import run_extra, run_guarded

        result = run_guarded(
            METRIC,
            UNIT,
            __file__,
            child_timeout=1800.0,
            # CPU fallback: shrink to something that finishes, still a
            # valid (clearly-labelled) record rather than a dead signal.
            cpu_env_defaults={
                "BENCH_BATCH": "1",
                "BENCH_FMAP": "16",
                "BENCH_STEPS": "3",
                # interpret-mode Pallas on CPU is far too slow for the
                # budget; the dense path is the CPU smoke
                "BENCH_ATTN": "dense",
            },
            # halve-microbatch-on-OOM ladder: BENCH_BATCH is the global
            # batch (BENCH_ACCUM scan-splits it), so the metric stays
            # comparable at batch 16 while the live microbatch shrinks.
            oom_ladder=[
                {"BENCH_ACCUM": "2"},
                {"BENCH_ACCUM": "4"},
                {"BENCH_ACCUM": "8"},
            ],
            microbatch_of=_microbatch_of,
            # fastest-first configuration ladder (BASELINE.md round-3
            # analysis: the step is HBM-bound, dense attention is ~60% of
            # traffic). Any failure falls through to the next profile;
            # the last is the round-3 known-good 7.2%-MFU config.
            profiles=[
                (
                    # fastest first: everything below PLUS 8 optimizer
                    # steps per dispatch (make_multi_step) — on the
                    # synchronous-dispatch tunnel the per-call round trip
                    # is a large fixed cost; r4 measured the same ~2s/step
                    # wall for dense AND flash programs, the signature of
                    # dispatch-bound timing.
                    "scan+flash+dots_policy+fused_ce+steps8",
                    {
                        "BENCH_EXECUTOR": "scan",
                        "BENCH_ATTN": "flash",
                        "BENCH_REMAT_POLICY": "dots_with_no_batch_dims_saveable",
                        "BENCH_FUSED_CE": "1",
                        "BENCH_SCAN_STEPS": "8",
                        "BENCH_STEPS": "32",
                    },
                ),
                (
                    # nn.scan executor first: ~12x smaller program. The
                    # tunneled backend's relay has died mid-compile on the
                    # unrolled flagship twice; the small compile is both
                    # faster and the best shot at surviving to a number.
                    "scan+flash+dots_policy+fused_ce",
                    {
                        "BENCH_EXECUTOR": "scan",
                        "BENCH_ATTN": "flash",
                        "BENCH_REMAT_POLICY": "dots_with_no_batch_dims_saveable",
                        "BENCH_FUSED_CE": "1",
                    },
                ),
                (
                    "flash+dots_policy+fused_ce",
                    {
                        "BENCH_ATTN": "flash",
                        "BENCH_REMAT_POLICY": "dots_with_no_batch_dims_saveable",
                        "BENCH_FUSED_CE": "1",
                    },
                ),
                (
                    # flash unavailable (e.g. Pallas can't compile through
                    # the backend): keep the non-attention wins
                    "dense+dots_policy+fused_ce",
                    {
                        "BENCH_ATTN": "dense",
                        "BENCH_REMAT_POLICY": "dots_with_no_batch_dims_saveable",
                        "BENCH_FUSED_CE": "1",
                    },
                ),
                ("baseline_dense_remat", {}),
            ],
        )

        # Opportunistic on-hardware artifacts: when the main bench got a
        # real TPU number, also record the inference north star, compiled
        # Pallas parity/timing, and component probes (VERDICT r3 items
        # that need real hardware) to a file the round snapshot commits.
        # Disable with BENCH_NO_EXTRA=1. stdout stays one JSON line.
        on_tpu = bool(
            result
            and result.get("ok")
            and not result.get("fallback")
            and "tpu" in str(result.get("device", "")).lower()
        )
        if on_tpu and os.environ.get("BENCH_NO_EXTRA") != "1":
            here = os.path.dirname(os.path.abspath(__file__))
            out = os.path.join(here, "EXTRA_RESULTS.jsonl")
            py = sys.executable
            # one combined wall budget for all extras so total bench.py
            # runtime stays bounded (main 1800s + probe 90s + this)
            extras_deadline = time.monotonic() + float(
                os.environ.get("BENCH_EXTRA_BUDGET", "1500")
            )
            for label, cmd in (
                ("generate_p50", [py, os.path.join(here, "bench_generate.py")]),
                # probes before the Pallas A/B: the isolated-kernel script
                # has blown the extras budget mid-compile (and preceded two
                # relay deaths) — it must not starve the cheap rows
                ("perf_probe",
                 [py, os.path.join(here, "scripts", "perf_probe.py"),
                  "peak", "hbm", "step", "attn", "ff", "logits"]),
                ("pallas_onchip",
                 [py, os.path.join(here, "scripts", "pallas_onchip.py")]),
            ):
                left = extras_deadline - time.monotonic()
                if left < 60:
                    # record the skip so "not in the file" can't be read
                    # as "never attempted"
                    with open(out, "a") as f:
                        f.write(json.dumps({
                            "experiment": label, "result": None,
                            "skipped": "extras budget exhausted",
                        }) + "\n")
                    continue
                run_extra(cmd, out, label, left)
