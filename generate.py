#!/usr/bin/env python
"""Generate images from a trained DALL-E checkpoint.

Equivalent of `/root/reference/generate.py`: loads the single-file
checkpoint (hparams + weights + frozen-VAE weights), verifies the VAE
class matches (`generate.py:101`), splits prompts on '|', optionally
completes the text first (--gentxt, `:116-118`), samples image tokens with
top-k 0.9 + temperature, decodes through the VAE and writes PNGs per
prompt directory (`:134-143`).

Sampling runs through the serving `GenerationEngine`
(`dalle_pytorch_tpu/serving/engine.py`) — the same padded fixed-shape
batching + fused dVAE decode + CLIP rerank code path `serve.py` exposes
over HTTP, so the CLI dogfoods the production path. `--no_cache` keeps the
full-reforward sampling oracle for correctness spot checks.
"""

from __future__ import annotations

import argparse
from pathlib import Path

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dalle_path", type=str, required=True)
    p.add_argument("--text", type=str, required=True, help="'|'-separated prompts")
    p.add_argument("--num_images", type=int, default=16)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--top_k", type=float, default=0.9)
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--cond_scale", type=float, default=1.0)
    p.add_argument("--outputs_dir", type=str, default="outputs")
    p.add_argument(
        "--clip_path",
        type=str,
        default=None,
        help="CLIP checkpoint; generations are reranked by similarity "
        "(`dalle_pytorch.py:569-571`) and saved best-first",
    )
    p.add_argument("--gentxt", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--no_cache",
        action="store_true",
        help="use the full-reforward sampling oracle instead of KV-cached decode",
    )
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os as _os

    if _os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", _os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import generate_images, generate_texts
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.serving.engine import SampleSpec, engine_from_checkpoint
    from dalle_pytorch_tpu.utils.images import save_image_grid, to_uint8

    # one compiled shape: the CLI always dispatches full --batch_size
    # batches (the engine pads the final partial chunk)
    engine = engine_from_checkpoint(
        args.dalle_path,
        clip_path=args.clip_path,
        batch_shapes=(args.batch_size,),
        cond_scale=args.cond_scale,
    )
    model, variables, vae = engine.model, engine.variables, engine.vae
    tokenizer, cfg = engine.tokenizer, engine.cfg
    rng = jax.random.PRNGKey(args.seed)

    from PIL import Image

    dvae_decode = None
    # spread the user seed so --seed N and --seed N+1 give fully disjoint
    # per-image seed ranges (engine rows are seeded individually; plain
    # consecutive bases would make adjacent runs share most images)
    next_seed = (args.seed * 1_000_003) & 0x7FFFFFFF

    for raw_prompt in args.text.split("|"):
        prompt = raw_prompt.strip()
        if args.gentxt:
            ids = tokenizer.tokenize(prompt, cfg.model.text_seq_len, truncate_text=True)
            prefix_len = int((ids[0] != 0).sum())
            rng, r = jax.random.split(rng)
            completed = generate_texts(
                model, variables, r, jnp.asarray(ids), prefix_len=prefix_len
            )
            prompt = tokenizer.decode(
                completed[0],
                pad_tokens=set(
                    range(model.total_text_tokens - model.text_seq_len,
                          model.total_text_tokens)
                ),
            )
            print(f"completed text: {prompt!r}")

        text_ids = engine.tokenize(prompt)

        images = []
        for start in range(0, args.num_images, args.batch_size):
            n = min(args.batch_size, args.num_images - start)
            if args.no_cache:
                # full-reforward oracle, bypassing the engine on purpose
                chunk = jnp.asarray(np.repeat(text_ids[None], n, axis=0))
                rng, r = jax.random.split(rng)
                toks = generate_images(
                    model, variables, r, chunk,
                    filter_thres=args.top_k, temperature=args.temperature,
                    cond_scale=args.cond_scale,
                )
                if isinstance(vae, DiscreteVAE):
                    if dvae_decode is None:
                        # jit once: eager decode dispatches per-op (slow on
                        # remote backends); shapes are fixed across chunks
                        dvae_decode = jax.jit(
                            lambda p, t: vae.apply(
                                {"params": p}, t, method=DiscreteVAE.decode
                            )
                        )
                    imgs = dvae_decode(engine.vae_params, toks)
                    images.append(np.asarray(imgs) * 0.5 + 0.5)  # un-normalize
                else:  # pretrained wrappers decode to [0,1] already
                    images.append(np.asarray(vae.decode(toks)))
                continue
            specs = [
                SampleSpec(
                    text_ids=text_ids,
                    seed=next_seed + i,
                    temperature=args.temperature,
                    top_k=args.top_k,
                )
                for i in range(n)
            ]
            next_seed += n
            _, pixels = engine.generate(specs)
            assert pixels is not None, "checkpoint has no VAE to decode pixels"
            images.append(pixels)
        images = np.concatenate(images, axis=0)

        if engine.clip is not None:
            images, scores, _ = engine.rerank(prompt, images)
            print("clip scores (best first):", np.asarray(scores)[:8])

        safe = "".join(c if c.isalnum() or c in " -." else "" for c in prompt)
        out_dir = Path(args.outputs_dir) / (safe.strip().replace(" ", "_")[:100] or "prompt")
        out_dir.mkdir(parents=True, exist_ok=True)
        for i, img in enumerate(images):
            Image.fromarray(to_uint8(img)).save(out_dir / f"{i}.png")
        save_image_grid(images, out_dir / "grid.png")
        print(f"created {len(images)} images at {out_dir}")


if __name__ == "__main__":
    main()
