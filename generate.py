#!/usr/bin/env python
"""Generate images from a trained DALL-E checkpoint.

Equivalent of `/root/reference/generate.py`: loads the single-file
checkpoint (hparams + weights + frozen-VAE weights), verifies the VAE
class matches (`generate.py:101`), splits prompts on '|', optionally
completes the text first (--gentxt, `:116-118`), samples image tokens with
top-k 0.9 + temperature, decodes through the VAE and writes PNGs per
prompt directory (`:134-143`).
"""

from __future__ import annotations

import argparse
from pathlib import Path

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dalle_path", type=str, required=True)
    p.add_argument("--text", type=str, required=True, help="'|'-separated prompts")
    p.add_argument("--num_images", type=int, default=16)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--top_k", type=float, default=0.9)
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--cond_scale", type=float, default=1.0)
    p.add_argument("--outputs_dir", type=str, default="outputs")
    p.add_argument(
        "--clip_path",
        type=str,
        default=None,
        help="CLIP checkpoint; generations are reranked by similarity "
        "(`dalle_pytorch.py:569-571`) and saved best-first",
    )
    p.add_argument("--gentxt", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--no_cache",
        action="store_true",
        help="use the full-reforward sampling oracle instead of KV-cached decode",
    )
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os as _os

    if _os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", _os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import (
        generate_images, generate_images_cached, generate_texts,
    )
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer, dalle_from_config, load_dalle_checkpoint,
        dvae_from_hparams,
    )
    from dalle_pytorch_tpu.utils.images import save_image_grid, to_uint8

    ckpt_path = Path(args.dalle_path)
    assert ckpt_path.exists(), f"trained DALL-E {ckpt_path} must exist"
    cfg, dalle_params, vae_params, meta, _ = load_dalle_checkpoint(str(ckpt_path))

    assert meta.get("vae_class_name") == "DiscreteVAE" or vae_params is None, (
        "checkpoint was trained with a pretrained VAE wrapper; provide it"
    )
    if vae_params is None:
        from dalle_pytorch_tpu.training.pipeline import build_vae

        vae, vae_params = build_vae(cfg)
    else:
        assert meta.get("vae_hparams"), "checkpoint missing vae_hparams"
        vae = dvae_from_hparams(meta["vae_hparams"])
    fmap = vae.image_size // (2 ** vae.num_layers)

    tokenizer = build_tokenizer(cfg)
    if cfg.model.attn_impl == "ring":
        # ring attention is a training-time layout (sequence sharded over
        # the mesh sp axis); KV-cached decode never runs it, so a
        # ring-trained checkpoint generates with the dense/auto kernel
        cfg.model.attn_impl = "auto"
    # (scan checkpoints — masked attn types included — decode natively:
    # the cached path row-slices the traced pattern masks at the decode
    # position, parity-pinned in test_scan_executor.py)
    model = dalle_from_config(
        cfg, num_image_tokens=vae.num_tokens, image_fmap_size=fmap,
        vocab_size=max(tokenizer.vocab_size, 1),
    )
    variables = {"params": dalle_params}
    rng = jax.random.PRNGKey(args.seed)

    from PIL import Image

    dvae_decode = None
    clip = clip_params = None
    if args.clip_path:
        from dalle_pytorch_tpu.training.pipeline import load_clip_checkpoint

        clip, clip_params = load_clip_checkpoint(args.clip_path)

    for raw_prompt in args.text.split("|"):
        prompt = raw_prompt.strip()
        if args.gentxt:
            ids = tokenizer.tokenize(prompt, cfg.model.text_seq_len, truncate_text=True)
            prefix_len = int((ids[0] != 0).sum())
            rng, r = jax.random.split(rng)
            completed = generate_texts(
                model, variables, r, jnp.asarray(ids), prefix_len=prefix_len
            )
            prompt = tokenizer.decode(
                completed[0],
                pad_tokens=set(
                    range(model.total_text_tokens - model.text_seq_len,
                          model.total_text_tokens)
                ),
            )
            print(f"completed text: {prompt!r}")

        ids = tokenizer.tokenize(prompt, cfg.model.text_seq_len, truncate_text=True)
        text = jnp.asarray(np.repeat(ids, args.num_images, axis=0))

        images = []
        for start in range(0, args.num_images, args.batch_size):
            chunk = text[start : start + args.batch_size]
            rng, r = jax.random.split(rng)
            if not args.no_cache and isinstance(vae, DiscreteVAE):
                # fused sampler: tokens AND pixels from ONE dispatch (one
                # tunnel round trip per batch instead of two)
                _, imgs = generate_images_cached(
                    model, variables, r, chunk,
                    filter_thres=args.top_k, temperature=args.temperature,
                    cond_scale=args.cond_scale, vae=vae, vae_params=vae_params,
                )
                images.append(np.asarray(imgs) * 0.5 + 0.5)  # un-normalize
                continue
            sample_fn = generate_images if args.no_cache else generate_images_cached
            toks = sample_fn(
                model, variables, r, chunk,
                filter_thres=args.top_k, temperature=args.temperature,
                cond_scale=args.cond_scale,
            )
            if isinstance(vae, DiscreteVAE):
                if dvae_decode is None:
                    # jit once: eager decode dispatches per-op (slow on
                    # remote backends); shapes are fixed across chunks
                    dvae_decode = jax.jit(
                        lambda p, t: vae.apply({"params": p}, t, method=DiscreteVAE.decode)
                    )
                imgs = dvae_decode(vae_params, toks)
                images.append(np.asarray(imgs) * 0.5 + 0.5)  # un-normalize
            else:  # pretrained wrappers decode to [0,1] already
                images.append(np.asarray(vae.decode(toks)))
        images = np.concatenate(images, axis=0)

        if clip is not None:
            from dalle_pytorch_tpu.models.clip import rerank

            # mismatches would fail silently (XLA gather clamps OOB indices)
            assert images.shape[1] == clip.visual_image_size, (
                f"CLIP checkpoint expects {clip.visual_image_size}px images "
                f"but the VAE decodes {images.shape[1]}px"
            )
            assert tokenizer.vocab_size <= clip.num_text_tokens, (
                f"tokenizer vocab {tokenizer.vocab_size} exceeds CLIP "
                f"num_text_tokens {clip.num_text_tokens}"
            )
            clip_ids = tokenizer.tokenize(
                prompt, clip.text_seq_len, truncate_text=True
            )
            sorted_imgs, scores, _ = rerank(
                clip,
                {"params": clip_params},
                jnp.asarray(clip_ids),
                jnp.asarray(images),
                text_mask=jnp.asarray(clip_ids != 0),
            )
            images = np.asarray(sorted_imgs)
            print("clip scores (best first):", np.asarray(scores)[:8])

        safe = "".join(c if c.isalnum() or c in " -." else "" for c in prompt)
        out_dir = Path(args.outputs_dir) / (safe.strip().replace(" ", "_")[:100] or "prompt")
        out_dir.mkdir(parents=True, exist_ok=True)
        for i, img in enumerate(images):
            Image.fromarray(to_uint8(img)).save(out_dir / f"{i}.png")
        save_image_grid(images, out_dir / "grid.png")
        print(f"created {len(images)} images at {out_dir}")


if __name__ == "__main__":
    main()
