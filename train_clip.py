#!/usr/bin/env python
"""Train a CLIP reranker on a text-image dataset.

The reference provides the CLIP model (`dalle_pytorch.py:274-350`) and uses
it to rerank generations (`dalle_pytorch.py:569-571`, `generate.py` via
`--clip_path` here) but ships no trainer for it; this CLI completes the
loop so reranking works end-to-end. Dataset arguments mirror
train_dalle.py: `rainbow:N`, cub200, mnist, or an image folder.
"""

from __future__ import annotations

import argparse


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image_text_folder", type=str, required=True)
    p.add_argument("--output", type=str, default="clip.npz")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--learning_rate", type=float, default=3e-4)
    p.add_argument("--image_size", type=int, default=128)
    p.add_argument("--patch_size", type=int, default=16)
    p.add_argument("--text_seq_len", type=int, default=64)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--dim_latent", type=int, default=256)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--bpe_path", type=str, default=None)
    p.add_argument(
        "--executor", choices=("unrolled", "scan"), default="unrolled",
        help="layer executor for both encoders; scan compiles one layer "
        "body instead of depth copies (models/transformer.py)",
    )
    p.add_argument(
        "--steps_per_dispatch", type=int, default=1,
        help="optimizer steps scanned into one device dispatch "
        "(host-loop elimination; see training/steps.py make_multi_step)",
    )
    p.add_argument("--debug", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp
    import numpy as np

    from dalle_pytorch_tpu.models.clip import CLIP
    from dalle_pytorch_tpu.parallel import initialize_distributed

    # multi-host rendezvous (launch.py env vars / TPU pod auto); no-op
    # single-host. Must run before the first device query.
    initialize_distributed()
    from dalle_pytorch_tpu.training.config import TrainConfig
    from dalle_pytorch_tpu.training.steps import (
        TrainState, make_optimizer, make_clip_train_step, make_multi_step,
        stack_batches, window_iter, window_keys,
    )
    from dalle_pytorch_tpu.training.pipeline import (
        build_dataset, build_tokenizer, save_clip_checkpoint,
    )
    from dalle_pytorch_tpu.training.metrics import MetricsLogger, ThroughputMeter

    # reuse the shared dataset dispatch (rainbow:N / folders / tar shards)
    cfg = TrainConfig()
    cfg.image_text_folder = args.image_text_folder
    cfg.bpe_path = args.bpe_path
    cfg.truncate_captions = True
    cfg.model.text_seq_len = args.text_seq_len
    tokenizer = build_tokenizer(cfg)
    data = build_dataset(cfg, tokenizer, args.image_size)
    batches = lambda seed: data.batches(args.batch_size, shuffle_seed=seed)
    print(f"{len(data)} text-image pairs for training")

    clip = CLIP(
        dim_text=args.dim,
        dim_image=args.dim,
        dim_latent=args.dim_latent,
        num_text_tokens=max(tokenizer.vocab_size, 1),
        text_enc_depth=args.depth,
        text_seq_len=args.text_seq_len,
        text_heads=args.heads,
        visual_enc_depth=args.depth,
        visual_heads=args.heads,
        visual_image_size=args.image_size,
        visual_patch_size=args.patch_size,
        executor=args.executor,
    )
    text0 = jnp.ones((2, args.text_seq_len), jnp.int32)
    img0 = jnp.zeros((2, args.image_size, args.image_size, 3))
    params = jax.jit(clip.init)(jax.random.PRNGKey(0), text0, img0)["params"]
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(f"{n_params:,} parameters")

    state = TrainState.create(
        apply_fn=clip.apply, params=params,
        tx=make_optimizer(args.learning_rate, clip_grad_norm=1.0),
    )
    raw_step = make_clip_train_step(clip)
    step_fn = jax.jit(raw_step)
    spd = max(1, args.steps_per_dispatch)
    multi_fn = jax.jit(make_multi_step(raw_step, spd)) if spd > 1 else None
    logger = MetricsLogger(project="clip_tpu", config=vars(args),
                           debug=args.debug)
    meter = ThroughputMeter()

    rng = jax.random.PRNGKey(1)
    global_step = 0
    for epoch in range(args.epochs):
        for win in window_iter(batches(epoch), spd):
            prev_step = global_step
            # fold_in(step) keys (make_multi_step's prescription, as in
            # train_dalle.py): stream depends only on global_step, so runs
            # are invariant to --steps_per_dispatch and epoch tails
            if multi_fn is not None and len(win) == spd:
                stacked = stack_batches([
                    {"text": b["text"], "images": b["images"]} for b in win
                ])
                state, m = multi_fn(
                    state,
                    {k: jnp.asarray(v) for k, v in stacked.items()},
                    window_keys(rng, global_step, spd),
                )
                global_step += spd
            else:
                for batch in win:  # spd==1 or epoch tail: per-step replay
                    r = jax.random.fold_in(rng, global_step)
                    state, m = step_fn(
                        state,
                        {"text": jnp.asarray(batch["text"]),
                         "images": jnp.asarray(batch["images"])},
                        r,
                    )
                    global_step += 1
            if global_step // 10 > prev_step // 10:
                loss = float(m["loss"])
                print(f"epoch {epoch} step {global_step}: loss {loss:.4f}")
                logger.log({"loss": loss, "epoch": epoch}, step=global_step)
                sps = meter.update(global_step, args.batch_size)
                if sps:
                    logger.log({"samples_per_sec": sps}, step=global_step)
        save_clip_checkpoint(args.output, clip, state.params)
        print(f"epoch {epoch} done; checkpoint -> {args.output}")
    logger.finish()


if __name__ == "__main__":
    main()
