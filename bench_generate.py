"""Benchmark: p50 latency of KV-cached image generation.

The BASELINE.json inference north star: `generate.py` producing 256x256
samples (OpenAI-dVAE geometry: 1024 image tokens autoregressively decoded
through the scan-based KV cache). Prints ONE JSON line with the p50
end-to-end latency for one batch of samples (transformer decode only; VAE
pixel decode is a single extra forward and is reported separately).

Env overrides: GEN_BATCH (default 4), GEN_FMAP (32), GEN_RUNS (5),
GEN_COND_SCALE (1.0), GEN_PHASES=1 adds a per-phase breakdown (prefill
program vs the 1024-step decode scan vs dVAE pixel decode) so the p50 can
be attacked where the time actually is.
"""

from __future__ import annotations

import json
import os
import sys
import time

METRIC = "generate_p50_latency_batch"
UNIT = "s"


def main():
    import jax

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE, generate_images_cached

    batch = int(os.environ.get("GEN_BATCH", "4"))
    fmap = int(os.environ.get("GEN_FMAP", "32"))
    runs = int(os.environ.get("GEN_RUNS", "5"))
    cond_scale = float(os.environ.get("GEN_COND_SCALE", "1.0"))
    # "scan" decodes natively on the depth-stacked layout: one compiled
    # layer body, the smallest decode program through a fragile tunnel
    executor = os.environ.get("GEN_EXECUTOR", "unrolled")
    text_seq = 256

    model = DALLE(
        dim=1024, depth=12, heads=16, dim_head=64,
        num_image_tokens=8192, image_fmap_size=fmap,
        num_text_tokens=10000, text_seq_len=text_seq,
        shift_tokens=True, rotary_emb=True, executor=executor,
        dtype=jnp.bfloat16,
    )
    text = jnp.ones((batch, text_seq), jnp.int32)
    tokens = jnp.zeros((batch, fmap * fmap), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)

    def north_star_dvae():
        # the framework's 256px/8192-token DiscreteVAE geometry, shared by
        # the GEN_FUSED sampler and the GEN_PHASES vae-decode probe so the
        # two env-gated paths can never benchmark different models
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        v = DiscreteVAE(
            image_size=8 * fmap, num_layers=3, num_tokens=8192,
            codebook_dim=512, hidden_dim=64,
        )
        vp = jax.jit(v.init)(
            jax.random.PRNGKey(3), jnp.zeros((1, 8 * fmap, 8 * fmap, 3))
        )["params"]
        return v, vp

    fused_vae = None
    if os.environ.get("GEN_FUSED"):
        # end-to-end-pixels p50: dVAE pixel decode fused into the sampler
        # program (tokens AND pixels from one dispatch — the generate.py
        # production path for DiscreteVAE checkpoints)
        fused_vae, fused_vparams = north_star_dvae()

    def sample(rng):
        if fused_vae is not None:
            _, px = generate_images_cached(
                model, params, rng, text, cond_scale=cond_scale,
                vae=fused_vae, vae_params=fused_vparams,
            )
            return px
        return generate_images_cached(
            model, params, rng, text, cond_scale=cond_scale
        )

    # warmup / compile. int() readback forces completion: block_until_ready
    # is a no-op on some tunneled backends, which would time dispatch
    # instead of the decode itself.
    out = sample(jax.random.PRNGKey(1))
    int(jnp.asarray(out).ravel()[0])

    times = []
    for i in range(runs):
        t0 = time.perf_counter()
        out = sample(jax.random.PRNGKey(2 + i))
        int(jnp.asarray(out).ravel()[0])
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]

    phases = None
    if os.environ.get("GEN_PHASES") and fused_vae is not None:
        raise SystemExit(
            "GEN_PHASES with GEN_FUSED would fold the fused vae decode "
            "into decode_scan_s/per_token_ms (double-counted vs the "
            "separate vae_decode_s row) — run the phase breakdown on the "
            "unfused sampler"
        )
    if os.environ.get("GEN_PHASES"):
        # Phase split: time the prefill-only program separately; the decode
        # scan is (total - prefill) — no third compile needed. Each phase
        # is its own dispatch, so on synchronous tunnels both absolute
        # numbers carry one dispatch RTT; the SPLIT (which phase dominates)
        # is what this measures. dVAE pixel decode (the one extra forward
        # `generate.py` runs after sampling) is timed on the framework's
        # 256px/8192-token DiscreteVAE north-star geometry.
        from dalle_pytorch_tpu.models.dalle import DALLE as _D, init_decode_cache

        @jax.jit
        def prefill(variables, t):
            return model.apply(
                variables, t, init_decode_cache(model, t.shape[0]),
                method=_D.decode_prefill,
            )

        # mirror the e2e path's classifier-free-guidance batch doubling
        # (generate_images_cached stacks a null-text stream when
        # cond_scale != 1), else the split under-measures prefill
        ptext = (
            jnp.concatenate([text, jnp.zeros_like(text)], axis=0)
            if cond_scale != 1.0 else text
        )
        row, _cache = prefill(params, ptext)
        float(jnp.asarray(row).ravel()[0].astype(jnp.float32))  # compile
        pf_times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            row, _cache = prefill(params, ptext)
            float(jnp.asarray(row).ravel()[0].astype(jnp.float32))
            pf_times.append(time.perf_counter() - t0)
        pf_times.sort()
        pf50 = pf_times[len(pf_times) // 2]

        vae, vparams = north_star_dvae()
        toks0 = jnp.zeros((batch, fmap * fmap), jnp.int32)
        vdec = jax.jit(
            lambda p, t: vae.apply({"params": p}, t, method=type(vae).decode)
        )
        float(jnp.asarray(vdec(vparams, toks0)).ravel()[0])  # compile
        vd_times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            float(jnp.asarray(vdec(vparams, toks0)).ravel()[0])
            vd_times.append(time.perf_counter() - t0)
        vd_times.sort()
        vd50 = vd_times[len(vd_times) // 2]

        phases = {
            "prefill_s": round(pf50, 3),
            "decode_scan_s": round(p50 - pf50, 3),
            "per_token_ms": round((p50 - pf50) / (fmap * fmap) * 1e3, 3),
            "vae_decode_s": round(vd50, 3),
        }

    out = {
        "metric": METRIC,
        "value": round(p50, 3),
        "unit": UNIT,
        "ok": True,
        "vs_baseline": None,  # reference publishes no latency numbers
        "batch": batch,
        "image_tokens": fmap * fmap,
        "tokens_per_sec": round(batch * fmap * fmap / p50, 1),
        "device": jax.devices()[0].device_kind,
        "config": f"dim1024-depth12-fmap{fmap}-bs{batch}"
                  f"-cond{cond_scale}-bf16-cached"
                  f"{'-scan' if executor == 'scan' else ''}"
                  f"{'-fusedpx' if fused_vae is not None else ''}",
    }
    if phases is not None:
        out["phases"] = phases
    if jax.devices()[0].platform == "cpu":
        out["fallback"] = True  # CPU smoke record, not a perf signal
    print(json.dumps(out))


if __name__ == "__main__":
    from bench_common import ensure_compile_cache

    ensure_compile_cache()
    if "--child" in sys.argv:
        main()
    else:
        from bench_common import run_guarded

        run_guarded(
            METRIC,
            UNIT,
            __file__,
            # leaves headroom inside bench.py's BENCH_EXTRA_BUDGET (1500s)
            # for interpreter startup + the 90s device probe, so a run
            # started there can finish (and print its JSON) in time
            child_timeout=1300.0,
            cpu_env_defaults={
                "GEN_BATCH": "1",
                "GEN_FMAP": "8",
                "GEN_RUNS": "2",
            },
        )
