"""Shared wedge-guard harness for the bench entry points.

The TPU tunnel backend has a known failure mode where `jax.devices()`
hangs indefinitely for every process after a killed device job. A bench
that hangs (or dies with a stack trace) records nothing; the contract
with the driver is ONE JSON line, always. So every bench runs as:

  parent (never touches a JAX backend)
    ├─ probe subprocess: tiny matmul under a hard timeout → platform info
    └─ child subprocess: the real measurement under a generous timeout

and the parent turns every failure mode — wedged tunnel, OOM, crash,
hang — into a clean structured-failure JSON line with exit code 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

PROBE_CODE = (
    "import json, time, jax, jax.numpy as jnp\n"
    "t0 = time.perf_counter()\n"
    "x = jnp.ones((256, 256))\n"
    "y = float((x @ x).sum())\n"
    "d = jax.devices()[0]\n"
    "print(json.dumps({'platform': d.platform, 'device_kind': d.device_kind,\n"
    "                  'n_devices': jax.device_count(),\n"
    "                  'probe_s': round(time.perf_counter() - t0, 2),\n"
    "                  'matmul': y}))\n"
)


def _last_json_line(text: str):
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def probe_device(timeout: float = 90.0):
    """Tiny matmul in a subprocess. Returns device info dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    return _last_json_line(proc.stdout)


def emit_failure(metric: str, unit: str, error: str) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": 0,
                "unit": unit,
                "vs_baseline": 0.0,
                "ok": False,
                "error": error,
            }
        )
    )


def run_guarded(
    metric: str,
    unit: str,
    script: str,
    child_timeout: float = 1800.0,
    cpu_env_defaults: dict | None = None,
) -> None:
    """Probe, then run `script --child` and forward its JSON line.

    `cpu_env_defaults` are env vars applied (setdefault) when the probed
    platform is CPU, to shrink the workload to something that finishes.
    """
    info = probe_device()
    if info is None:
        emit_failure(
            metric,
            unit,
            "device probe failed: accelerator backend unavailable or wedged "
            "(timed small matmul did not complete in 90s)",
        )
        return

    env = dict(os.environ)
    if info.get("platform") == "cpu":
        for k, v in (cpu_env_defaults or {}).items():
            env.setdefault(k, v)

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(script), "--child"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            timeout=child_timeout,
            env=env,
        )
    except subprocess.TimeoutExpired:
        emit_failure(
            metric, unit, f"bench child exceeded {child_timeout:.0f}s watchdog"
        )
        return

    result = _last_json_line(proc.stdout)
    if proc.returncode != 0 or result is None:
        tail = "\n".join(
            (proc.stderr or proc.stdout or "").splitlines()[-12:]
        )
        emit_failure(
            metric,
            unit,
            f"bench child rc={proc.returncode}, no JSON produced: {tail}",
        )
        return
    print(json.dumps(result))
