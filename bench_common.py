"""Shared wedge-guard harness for the bench entry points.

The TPU tunnel backend has a known failure mode where `jax.devices()`
hangs indefinitely for every process after a killed device job. A bench
that hangs (or dies with a stack trace) records nothing; the contract
with the driver is ONE JSON line, always. So every bench runs as:

  parent (never touches a JAX backend)
    ├─ probe subprocess: tiny matmul under a hard timeout → platform info
    └─ child subprocess: the real measurement under a generous timeout

and the parent turns every failure mode — wedged tunnel, OOM, crash,
hang — into a clean structured-failure JSON line with exit code 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_CODE = (
    "import json, os, time, jax\n"
    "if os.environ.get('DALLE_TPU_FORCE_PLATFORM'):\n"
    "    jax.config.update('jax_platforms', os.environ['DALLE_TPU_FORCE_PLATFORM'])\n"
    "import jax.numpy as jnp\n"
    "t0 = time.perf_counter()\n"
    "x = jnp.ones((256, 256))\n"
    "y = float((x @ x).sum())\n"
    "d = jax.devices()[0]\n"
    "print(json.dumps({'platform': d.platform, 'device_kind': d.device_kind,\n"
    "                  'n_devices': jax.device_count(),\n"
    "                  'probe_s': round(time.perf_counter() - t0, 2),\n"
    "                  'matmul': y}))\n"
)


def _last_json_line(text: str):
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None



def ensure_compile_cache():
    """Persistent XLA executable cache at <repo>/.jax_cache (idempotent).

    A tunnel drop or OOM retry then re-uses the already-built executable
    instead of paying (and risking) the same giant remote compile again;
    harmless if the backend ignores it. Call before any jax import.
    """
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )


def probe_device(timeout: float = 90.0):
    """Tiny matmul in a subprocess. Returns device info dict or None.

    If the default (possibly tunneled-accelerator) backend hangs or dies —
    the wedged-tunnel failure mode — retries once with the CPU platform
    forced: a clearly-tagged CPU smoke record beats a zeroed round. An
    explicit user DALLE_TPU_FORCE_PLATFORM is respected and never
    overridden (one attempt, their platform).
    """
    attempts = (
        (False,) if os.environ.get("DALLE_TPU_FORCE_PLATFORM") else (False, True)
    )
    for force_cpu in attempts:
        env = dict(os.environ)
        if force_cpu:
            env["DALLE_TPU_FORCE_PLATFORM"] = "cpu"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", PROBE_CODE],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                timeout=timeout,
                env=env,
            )
        except subprocess.TimeoutExpired:
            continue
        if proc.returncode != 0:
            continue
        info = _last_json_line(proc.stdout)
        if info is not None:
            if force_cpu:
                info["forced_cpu"] = True
            return info
    return None


def emit_failure(metric: str, unit: str, error: str) -> None:
    # flush: the process may live on (extras) long after this line; an
    # unflushed pipe buffer could lose it if the driver kills us later
    print(
        json.dumps(
            {
                "metric": metric,
                "value": 0,
                "unit": unit,
                "vs_baseline": 0.0,
                "ok": False,
                "error": error,
            }
        ),
        flush=True,
    )


_OOM_SIGNATURES = (
    "RESOURCE_EXHAUSTED",
    "Allocation type: HLO temp",
    "out of memory",
    "OOM",
)


def _looks_like_oom(text: str) -> bool:
    return any(sig in text for sig in _OOM_SIGNATURES)


def run_guarded(
    metric: str,
    unit: str,
    script: str,
    child_timeout: float = 1800.0,
    cpu_env_defaults: dict | None = None,
    oom_ladder: list[dict] | None = None,
    microbatch_of=None,
    profiles: "list[tuple[str, dict]] | None" = None,
) -> "dict | None":
    """Probe, then run `script --child` and forward its JSON line.

    Returns the successful result dict (already printed), or None on every
    failure path (a structured-failure line is printed instead) — callers
    use this to gate follow-on work on a real result.

    `cpu_env_defaults` are env vars applied (setdefault) when the probed
    platform is CPU, to shrink the workload to something that finishes.

    `oom_ladder` is a list of env-override dicts tried in order whenever the
    child dies with an OOM signature (RESOURCE_EXHAUSTED / HLO-temp
    allocation failure). One bad geometry must never zero a round again
    (round-2 postmortem): each rung shrinks the workload (smaller microbatch
    + grad accumulation) and the final record notes how many retries it took.
    `child_timeout` is the TOTAL budget across all rungs, so the one-JSON-
    line contract holds under any outer driver deadline > child_timeout.

    `microbatch_of(env) -> int | None` (optional) reports the live
    microbatch implied by an env dict; rungs that are invalid (None) or
    don't shrink the microbatch below the last attempt that actually ran
    (e.g. the caller already set a larger accumulation) are skipped.

    `profiles` is an ordered list of (name, env-defaults) configurations:
    the first profile that produces a result wins, and ANY child failure
    (not just OOM) falls through to the next — so an aggressive fast
    configuration can be tried first with a known-good one as the safety
    net. Profile values are applied with setdefault, so explicit user env
    always wins. Within each profile the OOM accum-ladder still applies.
    Budget policy: each non-final profile gets HALF the remaining budget
    (the preferred configuration deserves the larger share; a hang there
    still leaves the other half for the safety net); the final profile
    gets everything left. On a CPU fallback (smoke run) profiles are
    skipped entirely — they encode accelerator trade-offs and would
    mislabel the record.
    """
    ensure_compile_cache()
    info = probe_device()
    if info is None:
        emit_failure(
            metric,
            unit,
            "device probe failed (90s cap per attempt; a forced-CPU retry "
            "also runs unless DALLE_TPU_FORCE_PLATFORM was set explicitly) "
            "— if even the CPU attempt failed, JAX itself is unusable here "
            "(broken install / import error), not just the accelerator",
        )
        return

    base_env = dict(os.environ)
    if info.get("forced_cpu"):
        # the accelerator backend is wedged; children must skip it too
        base_env["DALLE_TPU_FORCE_PLATFORM"] = "cpu"
    if info.get("platform") == "cpu":
        for k, v in (cpu_env_defaults or {}).items():
            base_env.setdefault(k, v)

    deadline = time.monotonic() + child_timeout
    rungs = [{}] + list(oom_ladder or [])
    prof_list = list(profiles or [("", {})])
    if info.get("platform") == "cpu" and os.environ.get(
        "BENCH_PROFILES_ON_CPU"
    ) != "1":
        # profiles encode accelerator trade-offs; a CPU smoke run with
        # them would mislabel the record (flash forced back to dense by
        # cpu_env_defaults but still stamped "flash"). Escape hatch for
        # harness tests: BENCH_PROFILES_ON_CPU=1.
        prof_list = [("", {})]
    last_error = ""
    n_run = 0
    if microbatch_of is not None and microbatch_of(base_env) is None:
        emit_failure(
            metric,
            unit,
            "invalid bench env: the configured batch/accum combination is "
            "not divisible (check BENCH_BATCH / BENCH_ACCUM)",
        )
        return

    for prof_idx, (prof_name, prof_env) in enumerate(prof_list):
        # budget sharing: a hanging child in an early profile must not
        # starve the safety-net profiles, but the FIRST (preferred) profile
        # gets half the budget rather than 1/len — a slow-but-successful
        # run there beats a fast fallback
        remaining_total = deadline - time.monotonic()
        profiles_left = len(prof_list) - prof_idx
        share = (
            remaining_total
            if profiles_left == 1
            else remaining_total / 2.0
        )
        prof_deadline = time.monotonic() + max(share, 60.0)
        prof_base = dict(base_env)
        for k, v in prof_env.items():
            prof_base.setdefault(k, v)
        last_mb = None
        for overrides in rungs:
            env = dict(prof_base)
            env.update(overrides)
            if microbatch_of is not None:
                mb = microbatch_of(env)
                if mb is None or (last_mb is not None and mb >= last_mb):
                    continue
            else:
                mb = None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                emit_failure(
                    metric,
                    unit,
                    f"bench budget ({child_timeout:.0f}s) exhausted after "
                    f"{n_run} attempt(s): {last_error}",
                )
                return None
            prof_remaining = prof_deadline - time.monotonic()
            if prof_remaining <= 0:
                break  # this profile's slice is spent; on to the next
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(script), "--child"],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    timeout=min(remaining, prof_remaining),
                    env=env,
                )
            except subprocess.TimeoutExpired:
                n_run += 1
                last_error = (
                    f"child timed out after {min(remaining, prof_remaining):.0f}s "
                    f"in profile {prof_name or 'default'!r}"
                )
                break  # hang: skip to the next (safer) profile
            n_run += 1
            last_mb = mb

            result = _last_json_line(proc.stdout)
            if proc.returncode == 0 and result is not None:
                if n_run > 1:
                    result["attempts"] = n_run
                if prof_name:
                    result["profile"] = prof_name
                # flush: extras may keep this process alive long after;
                # see emit_failure
                print(json.dumps(result), flush=True)
                return result

            err_text = proc.stderr or proc.stdout or ""
            last_error = "\n".join(err_text.splitlines()[-12:])
            if not _looks_like_oom(err_text):
                break  # non-OOM failure: try the next profile, not a
                # smaller microbatch of the same one

    emit_failure(
        metric,
        unit,
        f"bench child failed after {n_run} attempt(s), "
        f"no JSON produced: {last_error}",
    )
    return None


def run_extra(cmd: list, out_path: str, label: str, timeout: float) -> None:
    """Run an auxiliary measurement, appending its JSON lines to a file.

    Used for opportunistic on-hardware artifacts (generate p50, Pallas
    parity/timing, component probes) piggybacked on a successful main
    bench run — stdout stays reserved for the ONE main JSON line.

    The extra runs in its own process group and the WHOLE group is killed
    on timeout: these scripts spawn their own JAX children, and an
    orphaned device child would hold the accelerator and wedge every
    later extra.
    """
    import signal

    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=dict(os.environ),
        start_new_session=True,
    )
    try:
        stdout, _ = proc.communicate(timeout=timeout)
        stdout = stdout or ""
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # keep whatever JSON lines made it out before the cutoff
        try:
            stdout, _ = proc.communicate(timeout=10)
            stdout = stdout or ""
        except Exception:
            stdout = ""
    lines = [
        ln.strip() for ln in stdout.splitlines() if ln.strip().startswith("{")
    ]
    records = []
    for ln in lines:
        try:
            records.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    with open(out_path, "a") as f:
        if records:
            for rec in records:
                f.write(json.dumps({"experiment": label, "result": rec}) + "\n")
        else:
            f.write(json.dumps({"experiment": label, "result": None}) + "\n")
