// Native byte-level BPE tokenizer core (train / encode / decode).
//
// TPU-framework equivalent of the reference's youtokentome C++ BPE
// dependency (/root/reference/dalle_pytorch/tokenizer.py:232-266): the
// reference delegates fast BPE to an external C++ library; here the
// capability is provided natively. Tokenization is host-side work — the
// arrays it produces feed jit'ted TPU steps — so this is plain portable
// C++17 exposed through a C ABI for ctypes.
//
// Id space (matching the framework contract that id 0 is padding):
//   0         PAD
//   1         UNK (never produced by byte-level encoding; reserved)
//   2..257    raw bytes 0..255
//   258..     merge ranks, in training order
//
// Pre-tokenization: text is split into chunks of (optional single leading
// space) + run of non-space bytes. Merges never cross chunk boundaries.
// Decoding is exact byte concatenation, so encode->decode roundtrips.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <queue>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr int32_t kPad = 0;
constexpr int32_t kByteBase = 2;
constexpr int32_t kMergeBase = 258;

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

struct Model {
  // merge rank r creates token kMergeBase + r from (left[r], right[r])
  std::vector<int32_t> left, right;
  std::unordered_map<uint64_t, int32_t> rank;       // pair -> rank
  std::vector<std::string> token_bytes;             // id -> raw bytes

  void finalize() {
    token_bytes.resize(kMergeBase + left.size());
    token_bytes[kPad] = "";
    token_bytes[1] = "";
    for (int b = 0; b < 256; ++b)
      token_bytes[kByteBase + b] = std::string(1, static_cast<char>(b));
    for (size_t r = 0; r < left.size(); ++r) {
      token_bytes[kMergeBase + r] =
          token_bytes[left[r]] + token_bytes[right[r]];
      rank.emplace(pair_key(left[r], right[r]), static_cast<int32_t>(r));
    }
  }

  int32_t vocab_size() const {
    return kMergeBase + static_cast<int32_t>(left.size());
  }
};

// split into chunks: (optional one leading space) + non-space run.
// Lone whitespace runs are attached byte-by-byte to keep exact roundtrip.
std::vector<std::string> chunks_of(const std::string& text) {
  std::vector<std::string> out;
  size_t i = 0, n = text.size();
  while (i < n) {
    std::string chunk;
    if (text[i] == ' ' && i + 1 < n && text[i + 1] != ' ') {
      chunk.push_back(' ');
      ++i;
    }
    if (i < n && text[i] == ' ') {  // run of spaces (or trailing space)
      chunk.push_back(' ');
      ++i;
      out.push_back(chunk);
      continue;
    }
    while (i < n && text[i] != ' ') chunk.push_back(text[i++]);
    if (!chunk.empty()) out.push_back(chunk);
  }
  return out;
}

std::vector<int32_t> bytes_to_ids(const std::string& s) {
  std::vector<int32_t> ids;
  ids.reserve(s.size());
  for (unsigned char c : s) ids.push_back(kByteBase + c);
  return ids;
}

// Greedy BPE encode of one chunk: repeatedly apply the lowest-rank pair.
void encode_chunk(const Model& m, std::vector<int32_t>& ids) {
  while (ids.size() >= 2) {
    int32_t best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < ids.size(); ++i) {
      auto it = m.rank.find(pair_key(ids[i], ids[i + 1]));
      if (it != m.rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    ids[best_i] = kMergeBase + best_rank;
    ids.erase(ids.begin() + best_i + 1);
  }
}

std::vector<int32_t> encode_text(const Model& m, const std::string& text) {
  std::vector<int32_t> out;
  for (const auto& chunk : chunks_of(text)) {
    auto ids = bytes_to_ids(chunk);
    encode_chunk(m, ids);
    out.insert(out.end(), ids.begin(), ids.end());
  }
  return out;
}

// ------------------------------------------------------------- training

struct Word {
  std::vector<int32_t> ids;
  int64_t freq = 0;
};

struct Trainer {
  std::vector<Word> words;
  std::unordered_map<uint64_t, int64_t> pair_count;
  std::unordered_map<uint64_t, std::unordered_set<int32_t>> pair_words;

  void add_pair(uint64_t key, int64_t freq, int32_t word_idx) {
    pair_count[key] += freq;
    pair_words[key].insert(word_idx);
  }

  void count_all() {
    for (size_t w = 0; w < words.size(); ++w) {
      const auto& ids = words[w].ids;
      for (size_t i = 0; i + 1 < ids.size(); ++i)
        add_pair(pair_key(ids[i], ids[i + 1]), words[w].freq,
                 static_cast<int32_t>(w));
    }
  }

  // pairs whose count changed since last heap push (for lazy re-push)
  std::vector<uint64_t> touched;

  // merge the pair (a, b) -> new_id across all words containing it.
  // Per affected word: retract its pair contributions, rebuild, re-add —
  // O(word_len) and straightforwardly correct; the heap handles selection.
  void apply_merge(int32_t a, int32_t b, int32_t new_id) {
    uint64_t key = pair_key(a, b);
    auto wit = pair_words.find(key);
    if (wit == pair_words.end()) return;
    std::vector<int32_t> affected(wit->second.begin(), wit->second.end());

    for (int32_t w : affected) {
      auto& ids = words[w].ids;
      int64_t f = words[w].freq;
      bool contains = false;
      for (size_t i = 0; i + 1 < ids.size(); ++i)
        if (ids[i] == a && ids[i + 1] == b) {
          contains = true;
          break;
        }
      if (!contains) continue;  // stale membership entry
      for (size_t i = 0; i + 1 < ids.size(); ++i) {
        uint64_t k = pair_key(ids[i], ids[i + 1]);
        pair_count[k] -= f;
        touched.push_back(k);
      }
      std::vector<int32_t> merged;
      merged.reserve(ids.size());
      for (size_t i = 0; i < ids.size();) {
        if (i + 1 < ids.size() && ids[i] == a && ids[i + 1] == b) {
          merged.push_back(new_id);
          i += 2;
        } else {
          merged.push_back(ids[i++]);
        }
      }
      ids.swap(merged);
      for (size_t i = 0; i + 1 < ids.size(); ++i) {
        uint64_t k = pair_key(ids[i], ids[i + 1]);
        add_pair(k, f, w);
        touched.push_back(k);
      }
    }
    pair_count.erase(key);
    pair_words.erase(key);
  }
};

Model* train_model(const std::string& corpus, int32_t vocab_size) {
  auto* model = new Model();
  Trainer tr;
  {
    std::unordered_map<std::string, int64_t> word_freq;
    std::istringstream stream(corpus);
    std::string line;
    while (std::getline(stream, line))
      for (const auto& chunk : chunks_of(line)) ++word_freq[chunk];
    tr.words.reserve(word_freq.size());
    for (auto& kv : word_freq)
      tr.words.push_back({bytes_to_ids(kv.first), kv.second});
  }
  tr.count_all();

  // lazy max-heap over (count, key): entries are re-pushed when counts
  // change and validated against the live map on pop. Selection is fully
  // deterministic across platforms: std::pair ordering breaks count ties
  // on the packed (left<<32|right) key, never on hash-map iteration order.
  using Entry = std::pair<int64_t, uint64_t>;
  std::priority_queue<Entry> heap;
  for (const auto& kv : tr.pair_count) heap.emplace(kv.second, kv.first);

  int32_t target_merges = vocab_size - kMergeBase;
  for (int32_t r = 0; r < target_merges;) {
    if (heap.empty()) break;
    auto [count, key] = heap.top();
    heap.pop();
    auto it = tr.pair_count.find(key);
    if (it == tr.pair_count.end() || it->second != count) continue;  // stale
    if (count < 2) break;  // nothing worth merging
    int32_t a = static_cast<int32_t>(key >> 32);
    int32_t b = static_cast<int32_t>(key & 0xffffffffu);
    model->left.push_back(a);
    model->right.push_back(b);
    tr.apply_merge(a, b, kMergeBase + r);
    for (uint64_t k : tr.touched) {
      auto cit = tr.pair_count.find(k);
      if (cit != tr.pair_count.end() && cit->second > 0)
        heap.emplace(cit->second, k);
    }
    tr.touched.clear();
    ++r;
  }
  model->finalize();
  return model;
}

bool save_model(const Model& m, const std::string& path) {
  std::ofstream f(path);
  if (!f) return false;
  f << "NATIVEBPE v1\n" << m.left.size() << "\n";
  for (size_t r = 0; r < m.left.size(); ++r)
    f << m.left[r] << " " << m.right[r] << "\n";
  return static_cast<bool>(f);
}

Model* load_model(const std::string& path) {
  std::ifstream f(path);
  if (!f) return nullptr;
  std::string magic, version;
  f >> magic >> version;
  if (magic != "NATIVEBPE") return nullptr;
  size_t n;
  f >> n;
  auto* m = new Model();
  m->left.resize(n);
  m->right.resize(n);
  for (size_t r = 0; r < n; ++r) f >> m->left[r] >> m->right[r];
  if (!f) {
    delete m;
    return nullptr;
  }
  // ids must be byte tokens or earlier merges, else finalize() would index
  // out of bounds (corrupt / truncated / hand-edited file)
  for (size_t r = 0; r < n; ++r) {
    int32_t hi = kMergeBase + static_cast<int32_t>(r);
    if (m->left[r] < kByteBase || m->left[r] >= hi ||
        m->right[r] < kByteBase || m->right[r] >= hi) {
      delete m;
      return nullptr;
    }
  }
  m->finalize();
  return m;
}

}  // namespace

extern "C" {

void* bpe_train(const char* corpus, int32_t vocab_size) {
  return train_model(corpus, vocab_size);
}

void* bpe_load(const char* model_path) { return load_model(model_path); }

int bpe_save(void* handle, const char* model_path) {
  return save_model(*static_cast<Model*>(handle), model_path) ? 0 : -1;
}

void bpe_free(void* handle) { delete static_cast<Model*>(handle); }

int32_t bpe_vocab_size(void* handle) {
  return static_cast<Model*>(handle)->vocab_size();
}

// encode one text; returns number of ids (<= max_len after truncation)
int32_t bpe_encode(void* handle, const char* text, int32_t* out,
                   int32_t max_len) {
  auto ids = encode_text(*static_cast<Model*>(handle), text);
  int32_t n = static_cast<int32_t>(std::min<size_t>(ids.size(), max_len));
  std::copy(ids.begin(), ids.begin() + n, out);
  return static_cast<int32_t>(ids.size());
}

// threaded batch encode into a zero-padded [n_texts, max_len] buffer.
// texts are NUL-separated in one blob with offsets; returns 0, or the
// (1-based) index of the first text longer than max_len when
// truncate == 0 (mirroring the tokenize() overflow error contract).
int32_t bpe_encode_batch(void* handle, const char* blob,
                         const int64_t* offsets, int32_t n_texts,
                         int32_t* out, int32_t max_len, int32_t truncate,
                         int32_t n_threads) {
  const Model& m = *static_cast<Model*>(handle);
  std::vector<int32_t> overflow(std::max(n_threads, 1), 0);
  auto work = [&](int32_t t) {
    for (int32_t i = t; i < n_texts; i += n_threads) {
      std::string text(blob + offsets[i]);
      auto ids = encode_text(m, text);
      if (static_cast<int32_t>(ids.size()) > max_len && !truncate) {
        if (!overflow[t]) overflow[t] = i + 1;
        continue;
      }
      int32_t n = static_cast<int32_t>(std::min<size_t>(ids.size(), max_len));
      std::copy(ids.begin(), ids.begin() + n, out + int64_t(i) * max_len);
    }
  };
  if (n_threads <= 1) {
    n_threads = 1;
    work(0);
  } else {
    std::vector<std::thread> threads;
    for (int32_t t = 0; t < n_threads; ++t) threads.emplace_back(work, t);
    for (auto& th : threads) th.join();
  }
  // each thread records its own first overflow (its stripe is ascending),
  // so the global minimum is the first offending text overall — matching
  // the single-threaded tokenize() error contract.
  int32_t first = 0;
  for (int32_t t = 0; t < n_threads; ++t)
    if (overflow[t] && (!first || overflow[t] < first)) first = overflow[t];
  return first;
}

// decode ids -> utf-8 bytes; pad/unknown ids are skipped. Returns byte
// count written (excluding NUL); out must hold max_bytes.
int32_t bpe_decode(void* handle, const int32_t* ids, int32_t n, char* out,
                   int32_t max_bytes) {
  const Model& m = *static_cast<Model*>(handle);
  std::string s;
  for (int32_t i = 0; i < n; ++i) {
    int32_t id = ids[i];
    if (id <= kPad || id == 1 || id >= m.vocab_size()) continue;
    s += m.token_bytes[id];
  }
  int32_t nbytes = static_cast<int32_t>(
      std::min<size_t>(s.size(), max_bytes > 0 ? max_bytes - 1 : 0));
  std::memcpy(out, s.data(), nbytes);
  out[nbytes] = '\0';
  return nbytes;
}

}  // extern "C"
