#!/usr/bin/env python
"""Train DALL-E (TPU-native train_dalle).

Equivalent of `/root/reference/train_dalle.py`: resumes/builds the frozen
VAE and the DALLE transformer, streams host-sharded batches, runs the
jitted+sharded train step (forward and optional inverse objectives,
`:509-518`), logs loss/throughput/samples, checkpoints with rotation, and
steps a plateau LR scheduler per epoch (`:344-353,589-590`).

Usage:
  python train_dalle.py --image_text_folder <dir|rainbow[:N]|shards.tar>
      [--config cfg.yaml] [--exp ff] [--vae_path vae.npz]
      [--set model.depth=4] [--set mesh.fsdp=2] ...
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", type=str, default=None)
    p.add_argument("--image_text_folder", type=str, default=None)
    p.add_argument("--tokens_path", type=str, default=None,
                   help="precompute_tokens.py artifact; trains from tokens")
    p.add_argument("--vae_path", type=str, default=None)
    p.add_argument("--dalle_path", type=str, default=None, help="resume checkpoint")
    p.add_argument(
        "--resume", action="store_true",
        help="resume full train state from the latest Orbax step checkpoint "
             "in output_dir (preemption recovery)",
    )
    p.add_argument("--taming", action="store_true")
    p.add_argument("--exp", type=str, default=None, choices=["f", "ff", "r", "ro"])
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--learning_rate", type=float, default=None)
    p.add_argument("--debug", action="store_true")
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="config override, e.g. --set model.depth=4",
    )
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os as _os

    if _os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", _os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import generate_images
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.parallel import (
        MESH_AXES, make_mesh, batch_sharding, state_shardings,
        partition_params, is_root, put_host_batch, gather_to_host,
    )
    from dalle_pytorch_tpu.parallel import initialize_distributed

    # multi-host rendezvous (launch.py env vars / TPU pod auto); no-op
    # single-host. Must run before the first device query.
    initialize_distributed()
    from dalle_pytorch_tpu.training import (
        TrainState, make_optimizer, make_dalle_train_step, make_multi_step,
        window_keys,
        stack_batches, window_iter, ReduceLROnPlateau, set_learning_rate,
        get_learning_rate,
    )
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from dalle_pytorch_tpu.data.prefetch import Prefetcher
    from dalle_pytorch_tpu.training.config import load_config
    from dalle_pytorch_tpu.training.checkpoint import CheckpointManager
    from dalle_pytorch_tpu.training.metrics import (
        MetricsLogger, ThroughputMeter, ProfilerHook,
    )
    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer, build_dataset, build_vae, dalle_from_config,
        save_dalle_checkpoint, load_dalle_checkpoint, restore_opt_state,
    )
    from dalle_pytorch_tpu.utils import param_count

    cfg = load_config(args.config, args.set)
    resume_meta = None
    opt_leaves_resume = None
    if args.dalle_path:  # RESUME (`train_dalle.py:139-161`)
        cfg, dalle_params_resume, vae_params_resume, resume_meta, \
            opt_leaves_resume = load_dalle_checkpoint(args.dalle_path)
        for ov in args.set:
            k, v = ov.split("=", 1)
            from dalle_pytorch_tpu.training.config import _set_dotted

            _set_dotted(cfg, k.strip(), v.strip())
    for k in ("epochs", "batch_size", "learning_rate", "image_text_folder",
              "tokens_path", "vae_path", "exp"):
        v = getattr(args, k)
        if v is not None:
            setattr(cfg, k, v)
    if args.taming:
        cfg.taming = True
    if args.debug:
        cfg.debug = True
    cfg.resolve()

    tokenizer = build_tokenizer(cfg)
    vae, vae_params = build_vae(cfg)
    if args.dalle_path and vae_params_resume is not None:
        vae_params = vae_params_resume
    image_fmap_size = vae.image_size // (2 ** vae.num_layers)
    if cfg.tokens_path:
        # offline-precomputed tokens (precompute_tokens.py): the train step
        # skips the VAE encode entirely — the better TPU pattern
        from dalle_pytorch_tpu.data.loader import TokenDataset

        dataset = TokenDataset(
            cfg.tokens_path, tokenizer, cfg.model.text_seq_len
        )
        assert dataset.num_tokens == vae.num_tokens, (
            f"tokens were precomputed with a {dataset.num_tokens}-code VAE "
            f"but --vae_path has {vae.num_tokens}"
        )
        assert dataset.image_tokens.shape[1] == image_fmap_size**2, (
            f"tokens artifact has {dataset.image_tokens.shape[1]} tokens per "
            f"image (VAE {dataset.image_size}px/{dataset.num_layers} layers) "
            f"but the model expects {image_fmap_size}^2 = {image_fmap_size**2} "
            f"— wrong --tokens_path for this VAE?"
        )
    else:
        dataset = build_dataset(cfg, tokenizer, image_size=vae.image_size)
    try:
        print(f"{len(dataset)} image-text pairs for training")
    except TypeError:  # streaming tar shards have no cheap length
        print("streaming dataset for training (length unknown)")

    # mesh before model: attn_impl="ring" (mesh.sp > 1) shards the model's
    # attention over the sp axis, so the model needs the mesh at build time
    pp = max(1, int(getattr(cfg.mesh, "pp", 1)))
    if pp > 1:
        # pipeline parallelism: pure-pp 5-axis mesh (dp/fsdp/tp/sp all 1,
        # 'pp' carrying the stages) so the standard batch/state shardings
        # (replication here) and gpipe's 'pp' ppermute share one mesh
        if cfg.model.executor != "scan":
            raise ValueError(
                "mesh.pp > 1 requires model.executor=scan (the pipeline "
                "runs the depth-stacked scan layout)"
            )
        if cfg.model.attn_dropout or cfg.model.ff_dropout:
            raise ValueError(
                "mesh.pp > 1 requires attn_dropout=ff_dropout=0: the pp "
                "trunk is deterministic by design (models/dalle.py); use "
                "dp/fsdp/tp for dropout training"
            )
        if cfg.mode == "forward_reverse_partial":
            raise ValueError(
                "mesh.pp > 1 cannot run forward_reverse_partial (the "
                "pipeline owns the layer order; reversed-order execution "
                "is a sequential-trunk feature)"
            )
        if cfg.model.depth % pp:
            raise ValueError(f"model.depth={cfg.model.depth} not divisible by mesh.pp={pp}")
        micro = max(1, int(cfg.mesh.pp_micro))
        if (cfg.batch_size // max(1, cfg.ga_steps)) % micro:
            raise ValueError(
                f"mesh.pp_micro={micro} must divide the per-accum-step "
                f"batch ({cfg.batch_size}//{cfg.ga_steps}); lower pp_micro "
                "or raise batch_size"
            )
        if cfg.mesh.fsdp != 1 or cfg.mesh.tp != 1 or cfg.mesh.sp != 1 or (
            cfg.mesh.dp not in (1, -1)
        ):
            raise ValueError(
                "mesh.pp > 1 is a pure-pp mesh: set dp/fsdp/tp/sp to 1 "
                "(compose dp x pp via parallel/gpipe.pipeline_layers)"
            )
        devices = jax.devices()
        if pp > len(devices):
            raise ValueError(f"mesh.pp={pp} > {len(devices)} devices")
        if pp < len(devices):
            print(
                f"WARNING: mesh.pp={pp} uses {pp} of {len(devices)} devices"
                " — the rest sit idle (pure-pp mesh; compose dp x pp via "
                "parallel/gpipe.pipeline_layers for full utilization)"
            )
        mesh = Mesh(
            np.asarray(devices[:pp]).reshape(1, 1, 1, 1, pp),
            MESH_AXES + ("pp",),
        )
    else:
        mesh = make_mesh(
            dp=cfg.mesh.dp, fsdp=cfg.mesh.fsdp, tp=cfg.mesh.tp, sp=cfg.mesh.sp
        )
    model = dalle_from_config(
        cfg,
        num_image_tokens=vae.num_tokens,
        image_fmap_size=image_fmap_size,
        vocab_size=max(tokenizer.vocab_size, 1),
        sp_mesh=mesh,
    )

    # pipeline-parallel trunk: built OUTSIDE model.apply (flax intercepts
    # module construction inside a parent scope); the train step feeds it
    # the live transformer params each call
    pp_trunk = None
    if pp > 1:
        from dalle_pytorch_tpu.models.transformer import (
            Transformer, make_pipeline_trunk,
        )

        pp_trunk = make_pipeline_trunk(
            Transformer(**model.transformer_kwargs()), mesh, n_micro=micro
        )

    rng = jax.random.PRNGKey(cfg.seed)
    rng, init_rng = jax.random.split(rng)
    t0 = jnp.zeros((1, cfg.model.text_seq_len), jnp.int32)
    i0 = jnp.zeros((1, image_fmap_size**2), jnp.int32)
    params = model.init(init_rng, t0, i0)["params"]
    if args.dalle_path:
        params = dalle_params_resume
    print(f"{param_count(params):,} parameters")

    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(cfg.learning_rate, clip_grad_norm=cfg.clip_grad_norm),
    )
    resume_train = (resume_meta or {}).get("train", {})
    if opt_leaves_resume is not None:
        # full-state resume: Adam moments + injected lr + step counter come
        # back exactly (`/root/reference/train_dalle.py:330-338`)
        state = state.replace(
            opt_state=restore_opt_state(state.opt_state, opt_leaves_resume),
            step=int(resume_train.get("global_step", 0)),
        )

    state_sh = state_shardings(state, mesh)
    txt_sh = batch_sharding(mesh, extra_dims=1)
    state = jax.device_put(state, state_sh)

    in_step_encode = isinstance(vae, DiscreteVAE) and not cfg.tokens_path
    if in_step_encode:
        img_sh = batch_sharding(mesh, extra_dims=3)
        vae_sh = partition_params(vae_params, mesh)
        vae_params = jax.device_put(vae_params, vae_sh)
        batch_shardings = {"text": txt_sh, "images": img_sh}
        raw_step = make_dalle_train_step(
            model, vae=vae, mode=cfg.mode, grad_accum=cfg.ga_steps,
            null_cond_prob=cfg.null_cond_prob, pp_trunk=pp_trunk,
        )
        extra_shardings = (vae_sh,)
    else:
        # pretrained torch-backed VAE: encode on host, feed tokens
        batch_shardings = {"text": txt_sh, "image_tokens": txt_sh}
        raw_step = make_dalle_train_step(
            model, mode=cfg.mode, grad_accum=cfg.ga_steps,
            null_cond_prob=cfg.null_cond_prob, pp_trunk=pp_trunk,
        )
        extra_shardings = ()
    step_fn = jax.jit(
        raw_step,
        in_shardings=(state_sh, batch_shardings, None) + extra_shardings,
        out_shardings=(state_sh, None),
        donate_argnums=0,
    )
    # steps_per_dispatch>1: scan T optimizer steps into one dispatch
    # (make_multi_step) — host-loop elimination; window batches get a
    # leading unsharded step axis on top of the per-step batch specs
    steps_per_dispatch = max(1, int(cfg.steps_per_dispatch))
    multi_fn = None
    if steps_per_dispatch > 1:
        win_shardings = jax.tree.map(
            lambda sh: NamedSharding(mesh, P(None, *sh.spec)),
            batch_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )
        multi_fn = jax.jit(
            make_multi_step(raw_step, steps_per_dispatch),
            in_shardings=(state_sh, win_shardings, None) + extra_shardings,
            out_shardings=(state_sh, None),
            donate_argnums=0,
        )

    run_dir = Path(cfg.output_dir)
    ckpt = CheckpointManager(run_dir / "dalle_ckpt", keep_n=cfg.keep_n_checkpoints)
    orbax_resume_meta = None
    if args.resume:
        restored, orbax_resume_meta, rstep = ckpt.restore(state)
        if restored is not None:
            state = restored
            print(f"resumed full train state from Orbax step {rstep}")
        else:
            print("no Orbax checkpoint found in output_dir; starting fresh")
    logger = MetricsLogger(
        project=cfg.wandb_name, config={"cli": "train_dalle"},
        enabled=is_root(), debug=cfg.debug, out_dir=str(run_dir / "logs"),
        entity=cfg.wandb_entity,
    )
    from dalle_pytorch_tpu.utils.flops import (
        dalle_train_flops_per_sample, mfu as flops_mfu,
    )

    # mode-aware: forward_forward / forward_reverse_partial run two full
    # fwd+bwd passes per sample, so the MFU numerator counts both
    flops_per_sample = dalle_train_flops_per_sample(model, mode=cfg.mode)
    dvae_decode = None  # lazily-jitted sample decode
    meter = ThroughputMeter()
    profiler = ProfilerHook(cfg.flops_profiler)
    plateau = ReduceLROnPlateau() if cfg.lr_decay else None
    if plateau is not None and resume_train.get("plateau"):
        # scheduler state resumes too (`train_dalle.py:354-355`)
        plateau.load_state_dict(resume_train["plateau"])

    from dalle_pytorch_tpu.training.pipeline import dvae_hparams

    def export(path: Path, epoch: int):
        # gather_to_host is a COLLECTIVE when params/opt are sharded
        # across hosts (fsdp/tp) — every process runs it; only root writes
        params_h = gather_to_host(state.params)
        vae_h = None if not in_step_encode else gather_to_host(vae_params)
        opt_h = gather_to_host(state.opt_state)
        if is_root():
            save_dalle_checkpoint(
                str(path), cfg, params_h,
                vae_h,
                epoch, type(vae).__name__,
                vae_hparams=dvae_hparams(vae) if in_step_encode else None,
                opt_state=opt_h,
                train_meta={
                    "global_step": global_step,
                    "plateau": plateau.state_dict() if plateau else None,
                },
            )

    # fail-early smoke save (`train_dalle.py:488-491`)
    out_file = run_dir / f"{cfg.dalle_output_file_name}.npz"
    resume_epoch = (resume_meta or {}).get("epoch", 0)
    global_step = int(resume_train.get("global_step", 0))
    if orbax_resume_meta:
        resume_epoch = int(orbax_resume_meta.get("epoch", resume_epoch))
        global_step = int(orbax_resume_meta.get("step", global_step))
        if plateau is not None and orbax_resume_meta.get("plateau"):
            plateau.load_state_dict(orbax_resume_meta["plateau"])
    export(out_file, resume_epoch)
    shard = (jax.process_index(), jax.process_count())
    stop = False
    # mid-epoch resume: skip the batches the checkpointed run already
    # consumed this epoch, so resume ≡ uninterrupted (no double-training)
    skip_batches = int((orbax_resume_meta or {}).get("epoch_batch", 0))
    for epoch in range(resume_epoch, cfg.epochs):
        if stop:
            break
        epoch_losses = []
        last_loss = None
        epoch_batch = 0
        def host_arrays(batch):
            """Per-batch host-side prep: captions split off (the device
            pytree must match the step's in_shardings), sample-logging head
            row fetched while host-local, torch-backed VAE encoded."""
            caps = batch.get("captions")
            # host-local head row for root-only sample logging: the global
            # dev batch spans non-addressable devices on multi-host, so it
            # cannot be fetched there
            text_head = np.asarray(batch["text"][:1])
            if in_step_encode:
                host = {"text": batch["text"], "images": batch["images"]}
            else:
                if "image_tokens" in batch:  # precomputed (TokenDataset)
                    tokens = batch["image_tokens"]
                else:  # pretrained torch-backed VAE: host-side encode
                    tokens = vae.get_codebook_indices(jnp.asarray(batch["images"]))
                host = {"text": batch["text"], "image_tokens": tokens}
            return host, caps, text_head

        def assemble(batch):
            """Host->device batch assembly, run ahead of the step in the
            prefetch thread so decode/tokenize/transfer overlap compute
            (the DataLoader-workers equivalent, ref `:309-316`)."""
            host, caps, text_head = host_arrays(batch)
            dev = {
                k: put_host_batch(v, batch_shardings[k]) for k, v in host.items()
            }
            return dev, caps, text_head

        def assemble_window(win):
            """steps_per_dispatch batches -> one [T, ...] device window
            (one transfer per dispatch). An epoch-tail window shorter than
            T is assembled per-batch and replayed through the single-step
            program — same RNG/cadence semantics, no second window-sized
            compile."""
            if len(win) < steps_per_dispatch:
                return [assemble(b) for b in win], None, None
            hosts, caps, heads = zip(*[host_arrays(b) for b in win])
            stacked = stack_batches(list(hosts))
            dev = {
                k: put_host_batch(v, win_shardings[k]) for k, v in stacked.items()
            }
            return dev, caps[0], heads[0]

        raw_batches = dataset.batches(
            cfg.batch_size, shuffle_seed=cfg.seed + epoch, shard=shard,
            start_batch=skip_batches if epoch == resume_epoch else 0,
        )
        if steps_per_dispatch > 1:
            batch_iter = Prefetcher(
                window_iter(raw_batches, steps_per_dispatch),
                transform=assemble_window,
                depth=cfg.prefetch_depth,
            )
        else:
            batch_iter = Prefetcher(
                raw_batches, transform=assemble, depth=cfg.prefetch_depth
            )
        if epoch == resume_epoch and skip_batches:
            epoch_batch = skip_batches
            # carry the interrupted epoch's loss history so the epoch-end
            # plateau step sees the same inputs as an uninterrupted run —
            # even when the skip consumes the whole epoch
            epoch_losses = list(orbax_resume_meta.get("epoch_losses") or [])
            if orbax_resume_meta.get("last_loss") is not None:
                last_loss = float(orbax_resume_meta["last_loss"])
        try:
            for dev_batch, captions, text_head in batch_iter:
                profiler.before_step(global_step)
                prev_step = global_step
                # fold_in(global_step), not sequential split: the key stream
                # is a pure function of the step index, so a mid-epoch
                # resume replays the exact dropout/null-cond randomness an
                # uninterrupted run would use — and the multi-step window
                # passes the SAME per-step folded keys stacked, so
                # steps_per_dispatch never changes the randomness
                if multi_fn is not None and not isinstance(dev_batch, list):
                    keys = window_keys(rng, global_step, steps_per_dispatch)
                    if in_step_encode:
                        state, metrics = multi_fn(state, dev_batch, keys, vae_params)
                    else:
                        state, metrics = multi_fn(state, dev_batch, keys)
                    global_step += steps_per_dispatch
                    epoch_batch += steps_per_dispatch
                else:
                    singles = (
                        dev_batch if isinstance(dev_batch, list)
                        else [(dev_batch, captions, text_head)]
                    )
                    for dev_b, caps_i, head_i in singles:
                        captions, text_head = caps_i, head_i
                        r = jax.random.fold_in(rng, global_step)
                        if in_step_encode:
                            state, metrics = step_fn(state, dev_b, r, vae_params)
                        else:
                            state, metrics = step_fn(state, dev_b, r)
                        global_step += 1
                        epoch_batch += 1

                def crossed(interval):
                    # cadences fire on interval CROSSINGS so a >1-step
                    # dispatch can't step over them; with stride 1 this is
                    # exactly the old `global_step % interval == 0`
                    return bool(interval) and (
                        global_step // interval > prev_step // interval
                    )

                last_loss = metrics["loss"]  # lazy device scalar; no sync here
                log = {}
                if crossed(10):
                    step_loss = float(last_loss)
                    epoch_losses.append(step_loss)
                    log.update(
                        epoch=epoch, iter=global_step, loss=step_loss,
                        forward_loss=float(metrics.get("forward_loss", 0.0)),
                        inverse_loss=float(metrics.get("inverse_loss", 0.0)),
                    )
                    if "accuracy" in metrics:
                        log["accuracy"] = float(metrics["accuracy"])
                    print(epoch, global_step, f"loss - {step_loss:.5f}")

                if crossed(cfg.save_every_n_steps):
                    # pass the sharded state directly: Orbax handles
                    # cross-host-sharded arrays natively (and copies to
                    # host before its async write), where device_get would
                    # raise on non-addressable fsdp/tp shards
                    ckpt.save(
                        global_step, state,
                        metadata={
                            "epoch": epoch, "step": global_step,
                            "epoch_batch": epoch_batch,
                            "epoch_losses": epoch_losses,
                            "last_loss": (
                                float(last_loss) if last_loss is not None else None
                            ),
                            "plateau": plateau.state_dict() if plateau else None,
                        },
                    )

                # ALL processes run the sampling computation (it is an
                # SPMD program over the sharded params); only the logger
                # (enabled on root) writes the image
                if crossed(cfg.log_images_freq):
                    # in-loop sample generation in EVERY configuration —
                    # trainable dVAE, precomputed tokens, VQGAN/OpenAI — like
                    # the reference (`train_dalle.py:564-576`)
                    # (disjoint from the train-step keys: extra fold_in tag)
                    gr = jax.random.fold_in(jax.random.fold_in(rng, global_step), 1)
                    toks = generate_images(
                        model, {"params": state.params},
                        gr, jnp.asarray(text_head), filter_thres=0.9,
                    )
                    if isinstance(vae, DiscreteVAE):
                        if dvae_decode is None:
                            dvae_decode = jax.jit(lambda p, t: vae.apply(
                                {"params": p}, t, method=DiscreteVAE.decode))
                        image = np.asarray(
                            dvae_decode(vae_params, toks)
                        ) * 0.5 + 0.5  # dVAE decodes to [-1, 1]
                    else:  # pretrained wrappers decode straight to [0, 1]
                        image = np.asarray(vae.decode(toks))
                    caption = (captions or [None])[0] or tokenizer.decode(
                        text_head[0]
                    )
                    logger.log_images(image, caption, "image", global_step)

                rate = meter.update(global_step, cfg.batch_size)
                if rate is not None:
                    log["sample_per_sec"] = rate
                    # input-boundedness: share of wall time blocked on the host
                    # pipeline (~0 = fully overlapped)
                    log["input_wait_frac"] = round(batch_iter.wait_fraction, 4)
                    # live MFU vs this chip's bf16 peak (reference logs
                    # only sample_per_sec)
                    # rate is PER-PROCESS samples/s (each host iterates its
                    # own data shard), so normalize by the local chip count
                    log["mfu"] = round(
                        flops_mfu(rate, flops_per_sample,
                                  jax.devices()[0].device_kind,
                                  jax.local_device_count()), 4)
                    print(epoch, global_step, f"sample_per_sec - {rate:.2f}")
                if log:
                    logger.log(log, step=global_step)
                if profiler.after_step(global_step):
                    print("Profiler has finished running. Stopping training early.")
                    stop = True
                    break

        finally:
            batch_iter.close()

        if plateau is not None and last_loss is not None:
            # epoch-average of the sampled losses (+ the final step), the
            # reference's scheduler signal (`train_dalle.py:589-590`)
            epoch_losses.append(float(last_loss))
            new_lr = plateau.step(
                float(np.mean(epoch_losses)), get_learning_rate(state)
            )
            state = set_learning_rate(state, new_lr)
        # epoch+1: this epoch is DONE — a --dalle_path resume starts the
        # next one (epoch would retrain data the restored Adam already saw)
        export(out_file, epoch + 1)
        logger.log_model_artifact(out_file)  # `train_dalle.py:481-484`

    export(out_file, cfg.epochs)
    ckpt.wait()
    logger.finish()
    print(f"final checkpoint -> {out_file}")


if __name__ == "__main__":
    main()
