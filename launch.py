"""Multi-host launcher for TPU pods and CPU/GPU fleets.

The reference ships SLURM/submitit launchers with automatic requeue
(`/root/reference/config/hydra/launcher/grogu.yaml`, `matrix.yaml`,
hydra-submitit). The TPU-native equivalent is thinner by design: on Cloud
TPU pods, `jax.distributed.initialize()` auto-detects the coordinator and
process topology from the TPU metadata service, so a "launcher" only needs
to (1) run the same command on every host, (2) wire rendezvous flags when
auto-detection is unavailable (CPU/GPU fleets, SLURM), and (3) requeue on
preemption — resume is already free via `--resume` (Orbax full-state
checkpoints, mid-epoch position included).

Usage — on every host of the fleet (rank and count from SLURM when
present, else flags):

  python launch.py --coordinator 10.0.0.1:1234 --num-hosts 4 --host-id 0 \
      -- train_dalle.py --image_text_folder data/ --resume ...

  # SLURM (one task per host); requeue-on-preemption with --requeue:
  srun python launch.py --requeue -- train_dalle.py ... --resume

  # TPU pod slice (args auto-detected, launch.py is optional):
  gcloud compute tpus tpu-vm ssh $TPU --worker=all \
      --command="cd repo && python launch.py -- train_dalle.py ... --resume"

The child inherits DALLE_TPU_COORDINATOR / DALLE_TPU_NUM_PROCS /
DALLE_TPU_PROC_ID; the trainers call `initialize_distributed()` which reads
them (or TPU auto-detection) before the first jax call.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys

# exit codes that mean "the scheduler preempted us", worth a requeue
_PREEMPT_CODES = {-signal.SIGTERM, -signal.SIGINT, 143, 130}


def first_slurm_host(nodelist: str) -> str:
    """First hostname of a SLURM nodelist expression.

    Handles plain lists ("a,b"), bracket ranges ("node[1-4]") and
    hyphenated names with ranges ("gpu-node-[01-04,07]") — the prefix
    before "[" concatenated with the first index of the range.
    """
    if not nodelist:
        return ""
    head = nodelist.split(",")[0] if "[" not in nodelist else nodelist
    if "[" in head:
        prefix, rest = head.split("[", 1)
        first_idx = rest.split(",")[0].split("-")[0].rstrip("]")
        return prefix + first_idx
    return head


def slurm_defaults() -> dict:
    """Rendezvous info from SLURM env (the reference's submitit launchers
    run under the same variables)."""
    env = os.environ
    if not env.get("SLURM_PROCID"):  # absent or empty (cleared)
        return {}
    nodelist = env.get("SLURM_STEP_NODELIST", env.get("SLURM_NODELIST", ""))
    first = first_slurm_host(nodelist)
    return {
        "host_id": int(env["SLURM_PROCID"]),
        "num_hosts": int(env.get("SLURM_NTASKS", "1")),
        "coordinator": f"{first}:12345" if first else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (omit on TPU pods: auto)")
    ap.add_argument("--num-hosts", type=int, default=None)
    ap.add_argument("--host-id", type=int, default=None)
    ap.add_argument("--requeue", action="store_true",
                    help="relaunch the command after preemption-style exits "
                         "(SIGTERM/SIGINT); combine with --resume for exact "
                         "continuation")
    ap.add_argument("--max-requeues", type=int, default=100)
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="-- script.py args...")
    args = ap.parse_args(argv)

    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given; usage: launch.py [flags] -- train_dalle.py ...")

    slurm = slurm_defaults()
    coordinator = args.coordinator or slurm.get("coordinator")
    num_hosts = args.num_hosts if args.num_hosts is not None else slurm.get("num_hosts")
    host_id = args.host_id if args.host_id is not None else slurm.get("host_id")

    env = dict(os.environ)
    if coordinator:
        env["DALLE_TPU_COORDINATOR"] = coordinator
    if num_hosts is not None:
        env["DALLE_TPU_NUM_PROCS"] = str(num_hosts)
    if host_id is not None:
        env["DALLE_TPU_PROC_ID"] = str(host_id)
    if coordinator is None and num_hosts is None and host_id is None:
        # no explicit rendezvous anywhere: the TPU-pod case — tell
        # initialize_distributed() to run jax.distributed.initialize()
        # with full auto-detection (metadata service)
        env.setdefault("DALLE_TPU_DIST", "1")

    # Schedulers preempt by signalling the whole process group; without a
    # handler the launcher would die alongside the child and the requeue
    # loop below would never run. Forward the signal, reap the child, then
    # decide to requeue.
    pending_sig = []

    def forward(signum, frame):
        pending_sig.append(signum)
        if child[0] is not None and child[0].poll() is None:
            child[0].send_signal(signum)

    child = [None]
    old_handlers = {
        s: signal.signal(s, forward) for s in (signal.SIGTERM, signal.SIGINT)
    }

    full = [sys.executable, *cmd]
    attempts = 0
    try:
        while True:
            pending_sig.clear()
            child[0] = subprocess.Popen(full, env=env)
            rc = child[0].wait()
            if rc == 0:
                return 0
            preempted = rc in _PREEMPT_CODES or bool(pending_sig)
            if not args.requeue or not preempted:
                return rc
            attempts += 1
            if attempts > args.max_requeues:
                print(
                    f"launch.py: giving up after {attempts - 1} requeues",
                    file=sys.stderr,
                )
                return rc
            print(
                f"launch.py: command exited {rc} (preemption-style); "
                f"requeue {attempts}/{args.max_requeues}",
                file=sys.stderr,
            )
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)


if __name__ == "__main__":
    sys.exit(main())
