"""Closed-loop serving benchmark: requests/sec vs. batch occupancy.

Drives the real `GenerationEngine` + `MicroBatcher` (no HTTP, no
checkpoint — a tiny randomly-initialized model) with N closed-loop client
threads, sweeping N. Each client submits one request after another, so
offered load scales with concurrency and the micro-batcher's
deadline-or-capacity policy determines how many rows coalesce per
dispatch. Prints ONE JSON line (BENCH_* contract) with the sweep and a
headline req/s at the top concurrency.

Env overrides: SERVE_SWEEP ("1,4,8" client counts), SERVE_REQUESTS (per
client, default 8), SERVE_BATCH_SHAPES ("1,4,8"), SERVE_DELAY_MS (25),
SERVE_DIM/SERVE_DEPTH/SERVE_FMAP/SERVE_TEXT_SEQ for the toy model.
"""

from __future__ import annotations

import json
import os
import threading
import time

METRIC = "serving_rps_top_concurrency"
UNIT = "req/s"


def build_engine():
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.serving.engine import GenerationEngine

    dim = int(os.environ.get("SERVE_DIM", "64"))
    depth = int(os.environ.get("SERVE_DEPTH", "2"))
    fmap = int(os.environ.get("SERVE_FMAP", "4"))
    text_seq = int(os.environ.get("SERVE_TEXT_SEQ", "16"))
    shapes = tuple(
        int(b) for b in os.environ.get("SERVE_BATCH_SHAPES", "1,4,8").split(",")
    )

    vae = DiscreteVAE(
        image_size=4 * fmap, num_layers=2, num_tokens=64,
        codebook_dim=32, hidden_dim=16,
    )
    vae_params = jax.jit(vae.init)(
        jax.random.PRNGKey(1), jnp.zeros((1, 4 * fmap, 4 * fmap, 3))
    )["params"]

    model = DALLE(
        dim=dim, depth=depth, heads=2, dim_head=dim // 2,
        num_image_tokens=64, image_fmap_size=fmap,
        num_text_tokens=256, text_seq_len=text_seq,
        shift_tokens=False, rotary_emb=True,
    )
    text = jnp.zeros((1, text_seq), jnp.int32)
    tokens = jnp.zeros((1, fmap * fmap), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)

    engine = GenerationEngine(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        batch_shapes=shapes,
    )
    return engine, np.zeros(text_seq, np.int32)


def run_level(engine, text_ids, concurrency: int, requests_per_client: int,
              delay_ms: float):
    import numpy as np

    from dalle_pytorch_tpu.serving.batcher import MicroBatcher
    from dalle_pytorch_tpu.serving.engine import SampleSpec
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    registry = MetricsRegistry()
    batcher = MicroBatcher(
        engine, max_delay_ms=delay_ms,
        max_queue_rows=max(64, 4 * concurrency), registry=registry,
    )
    latencies, errors = [], []
    lock = threading.Lock()

    def client(cid: int):
        for i in range(requests_per_client):
            t0 = time.perf_counter()
            try:
                req = batcher.submit(
                    [SampleSpec(text_ids, seed=cid * 10_000 + i)],
                    timeout_s=120.0,
                )
                req.future.result(timeout=120.0)
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                with lock:
                    errors.append(repr(e))
                continue
            with lock:
                latencies.append(time.perf_counter() - t0)

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    batcher.shutdown(drain=True)

    occ = registry.get("dalle_serving_batch_occupancy_rows")
    lat = sorted(latencies)
    done = len(lat)
    return {
        "concurrency": concurrency,
        "requests": done,
        "errors": len(errors),
        "wall_s": round(wall, 3),
        "rps": round(done / wall, 3) if wall > 0 else None,
        # rows actually flushed through the engine (1 per request today,
        # but counted from the occupancy histogram so multi-image requests
        # stay honest)
        "images_per_s": round(occ.sum / wall, 3) if wall > 0 else None,
        "p50_ms": round(lat[done // 2] * 1000, 1) if done else None,
        "p95_ms": round(lat[min(done - 1, int(0.95 * done))] * 1000, 1)
        if done else None,
        "mean_batch_occupancy": round(occ.mean(), 2),
        "batches": int(occ.count),
    }


def main():
    sweep = [
        int(c) for c in os.environ.get("SERVE_SWEEP", "1,4,8").split(",")
    ]
    requests_per_client = int(os.environ.get("SERVE_REQUESTS", "8"))
    delay_ms = float(os.environ.get("SERVE_DELAY_MS", "25"))

    engine, text_ids = build_engine()
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    results = [
        run_level(engine, text_ids, c, requests_per_client, delay_ms)
        for c in sweep
    ]
    top = results[-1]
    import jax

    record = {
        "metric": METRIC,
        "value": top["rps"],
        "unit": UNIT,
        "ok": all(r["errors"] == 0 for r in results),
        "device": jax.devices()[0].platform,
        "warmup_s": round(warmup_s, 2),
        "compiled_shapes": list(engine.stats.compiled_shapes),
        "max_delay_ms": delay_ms,
        "requests_per_client": requests_per_client,
        "sweep": results,
    }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
