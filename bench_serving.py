"""Serving benchmark: closed-loop sweep and open-loop engine comparison.

Closed-loop mode (default, BENCH_* contract): drives the real
`GenerationEngine` + `MicroBatcher` (no HTTP, no checkpoint — a tiny
randomly-initialized model) with N closed-loop client threads, sweeping N.
Each client submits one request after another, so offered load scales with
concurrency and the micro-batcher's deadline-or-capacity policy determines
how many rows coalesce per dispatch. Prints ONE JSON line with the sweep
and a headline req/s at the top concurrency.

Open-loop mode (`--mode open-loop`): Poisson arrivals at a fixed rate
against BOTH engines — the padded micro-batch `GenerationEngine` and the
continuous-batching `ContinuousEngine` — over the SAME toy weights and the
SAME pre-drawn arrival schedule. Emits one JSON line per engine with
sustained req/s and time-to-first-token percentiles; the continuous line
carries the micro-relative ratios. This is the acceptance instrument for
the continuous-batching PR: token-boundary admission must show >= 1.5x
sustained req/s or <= 0.5x p95 TTFT at equal load.

Env overrides: SERVE_SWEEP ("1,4,8" client counts), SERVE_REQUESTS (per
client, default 8), SERVE_BATCH_SHAPES ("1,4,8"), SERVE_DELAY_MS (25),
SERVE_DIM/SERVE_DEPTH/SERVE_FMAP/SERVE_TEXT_SEQ for the toy model;
open-loop: SERVE_RATE_RPS (default auto-calibrated), SERVE_OPEN_SECONDS
(10), SERVE_CHUNK_TOKENS (4), SERVE_PREFILL_BATCH (4), SERVE_ARRIVAL_SEED
(0). The continuous JSON line reports admission-dispatch accounting
(prefill_dispatches / prefill_rows_per_dispatch) so the batched-prefill
amortization is visible in the output. Both open-loop lines carry a
`stages` per-stage breakdown ({stage: {mean_ms, count}} deltas from the
`dalle_serving_stage_seconds` family over the measured window only), so
a TTFT regression is attributable to queue vs prefill vs chunk without
re-running under a tracer. The continuous line additionally carries a
`vitals` block (obs/vitals.py sampler over the measured window only:
mean/peak slots_active — blocks too on the paged layout — plus per-
program MFU where the cost table measured a synced dispatch).

Paged KV cache (`--kv_layout paged`, SERVE_PAGE_SIZE / SERVE_KV_PAGES):
the continuous engine becomes `PagedContinuousEngine` and its line gains
`block_occupancy` (measured-window peak pages vs the slotted layout's
always-resident worst case) and `prefix_cache` / `prefix_hit_rate` with
hit-vs-cold TTFT splits. `--prompt_reuse P` (SERVE_PROMPT_REUSE) makes P
of the arrivals repeat a prompt from a Zipf-ish popularity pool — the
workload on which prefix caching turns repeat admissions into
near-zero-cost TTFT; both engines replay the identical prompt schedule.

Mesh-sharded serving (`--mesh tp=2`, SERVE_MESH): the continuous side
runs as `ShardedContinuousEngine` — or `ShardedPagedContinuousEngine`
when combined with `--kv_layout paged` (the page pool head-splits, page
tables stay host-side) — over a `make_mesh` device mesh, and its JSON
line gains a `mesh` block — axis sizes, per-device state-buffer bytes,
and the per-device memory PEAK over the measured window. On CPU pair it
with XLA_FLAGS=--xla_force_host_platform_device_count=8.

Quantized KV cache (`--kv_dtype int8`, SERVE_KV_DTYPE): the continuous
engine stores its KV pages/lanes as int8 with per-(position, head)
scales (dequantized inside the decode kernels), and its JSON line gains
a `quality` block: the SAME (prompt, seed) rows generated through the
bf16 micro engine and the quantized engine, scored by a toy CLIP —
clip_mean_ref / clip_mean_quantized / clip_delta_mean put the quality
cost beside the `kv_bytes_per_slot` capacity win (speed AND quality,
never speed alone).

Priority mix (`--priority_mix P`, SERVE_PRIORITY_MIX): the QoS acceptance
instrument. Open-loop Poisson arrivals at an OVERLOAD rate
(SERVE_PRIORITY_OVERLOAD x the continuous engine's measured saturation,
default 1.3) against ONE continuous batcher with preemption + deadline
shedding on; each arrival is "high" with probability P, "low" otherwise
(bimodal). The JSON line reports per-class completion and TTFT
percentiles, the preemption/resumption/shed counter families, and
`high_ttft_p95_ratio_vs_unloaded` — high-priority p95 TTFT against the
same batcher's measured UNLOADED baseline. The QoS claim is that ratio
staying small (the low class absorbs the overload via preemption and
shedding) while low-class p95 degrades.

Fleet mode (`--replicas N`, SERVE_REPLICAS): robustness instrument for
the replica router. N in-process continuous replicas behind a real
`FleetRouter` take the same open-loop Poisson schedule twice over HTTP —
once healthy, once with one replica HARD-KILLED 30% into the window. The
JSON line reports both windows' completion and latency percentiles, the
p95 killed-vs-healthy ratio, and the router's failover/hedge/ejection
accounting; the headline value is the killed-window completion fraction
(the chaos claim is 1.0 — failover retries absorb the crash).
SERVE_FLEET_SECONDS (6) / SERVE_FLEET_RPS (auto) / SERVE_FLEET_SLOTS (4)
/ SERVE_HEDGE_MS (off) size it.

Streaming previews (`--stream`, SERVE_STREAM=1): the progressive-preview
acceptance instrument. One continuous engine warmed WITH the preview
fill-decode program (`preview_enabled=True`), open-loop Poisson arrivals
each submitted with a live `RequestStream` — the same object an SSE
client hangs on — so every chunk boundary emits progress and every
SERVE_PREVIEW_EVERY (default 1) chunks pays the snapshot + preview
dispatch. The JSON line reports TTFP (time-to-first-preview) p50/p95
alongside TTFT and the headline `ttfp_p95_chunk_periods`: the p95 gap
between first preview and first token in measured chunk periods, which
the streaming PR accepts at <= ~2 (one period to reach a boundary, one
for the preview dispatch riding it). SERVE_STREAM_SECONDS (8) sizes the
window.

Fleet tracing (`--trace_export`, SERVE_TRACE_EXPORT=1): every measured
request is traced client-side (the bench plays the ingress role) and
shipped through a real `TraceExporter` to an in-process
`CollectorServer` — the same export path a serving replica uses — and
each engine's JSON line gains a `critical_path` block: per-stage fleet
p50/p95 and dominant-critical-path stage attribution over the measured
window only (tracers attach after calibration; the collector resets
between engines).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

METRIC = "serving_rps_top_concurrency"
UNIT = "req/s"


def build_toy(sparse=False):
    """Shared toy model/VAE weights so both engines serve identical work.

    `sparse=True` (the --decode_sparsity policy bench) gives the toy a
    pattern to exploit: alternating full/axial_row layers, the flash
    attention impl (sparse decode rides the flash kernel), and a KV tile
    width small enough relative to the toy's cache (SERVE_SPARSE_BLOCK,
    default 16) that the axial layer's dead tiles actually skip — the
    production default DECODE_SPARSE_BLOCK=128 would be one tile on a
    toy-sized cache and skip nothing."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE

    dim = int(os.environ.get("SERVE_DIM", "64"))
    depth = int(os.environ.get("SERVE_DEPTH", "2"))
    fmap = int(os.environ.get("SERVE_FMAP", "4"))
    text_seq = int(os.environ.get("SERVE_TEXT_SEQ", "16"))

    vae = DiscreteVAE(
        image_size=4 * fmap, num_layers=2, num_tokens=64,
        codebook_dim=32, hidden_dim=16,
    )
    vae_params = jax.jit(vae.init)(
        jax.random.PRNGKey(1), jnp.zeros((1, 4 * fmap, 4 * fmap, 3))
    )["params"]

    sparse_kw = {}
    if sparse:
        sparse_kw = dict(
            attn_types=("full", "axial_row"),
            attn_impl="flash",
            decode_sparse_block=int(
                os.environ.get("SERVE_SPARSE_BLOCK", "16")
            ),
        )
    model = DALLE(
        dim=dim, depth=depth, heads=2, dim_head=dim // 2,
        num_image_tokens=64, image_fmap_size=fmap,
        num_text_tokens=256, text_seq_len=text_seq,
        shift_tokens=False, rotary_emb=True, **sparse_kw,
    )
    text = jnp.zeros((1, text_seq), jnp.int32)
    tokens = jnp.zeros((1, fmap * fmap), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)
    return model, params, vae, vae_params, np.zeros(text_seq, np.int32)


def build_engine():
    from dalle_pytorch_tpu.serving.engine import GenerationEngine

    shapes = tuple(
        int(b) for b in os.environ.get("SERVE_BATCH_SHAPES", "1,4,8").split(",")
    )
    model, params, vae, vae_params, text_ids = build_toy()
    engine = GenerationEngine(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        batch_shapes=shapes,
    )
    return engine, text_ids


def run_level(engine, text_ids, concurrency: int, requests_per_client: int,
              delay_ms: float):
    import numpy as np

    from dalle_pytorch_tpu.serving.batcher import MicroBatcher
    from dalle_pytorch_tpu.serving.engine import SampleSpec
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    registry = MetricsRegistry()
    batcher = MicroBatcher(
        engine, max_delay_ms=delay_ms,
        max_queue_rows=max(64, 4 * concurrency), registry=registry,
    )
    latencies, errors = [], []
    lock = threading.Lock()

    def client(cid: int):
        for i in range(requests_per_client):
            t0 = time.perf_counter()
            try:
                req = batcher.submit(
                    [SampleSpec(text_ids, seed=cid * 10_000 + i)],
                    timeout_s=120.0,
                )
                req.future.result(timeout=120.0)
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                with lock:
                    errors.append(repr(e))
                continue
            with lock:
                latencies.append(time.perf_counter() - t0)

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    batcher.shutdown(drain=True)

    occ = registry.get("dalle_serving_batch_occupancy_rows")
    lat = sorted(latencies)
    done = len(lat)
    return {
        "concurrency": concurrency,
        "requests": done,
        "errors": len(errors),
        "wall_s": round(wall, 3),
        "rps": round(done / wall, 3) if wall > 0 else None,
        # rows actually flushed through the engine (1 per request today,
        # but counted from the occupancy histogram so multi-image requests
        # stay honest)
        "images_per_s": round(occ.sum / wall, 3) if wall > 0 else None,
        "p50_ms": round(_percentile(lat, 0.5) * 1000, 1) if done else None,
        "p95_ms": round(_percentile(lat, 0.95) * 1000, 1) if done else None,
        "mean_batch_occupancy": round(occ.mean(), 2),
        "batches": int(occ.count),
    }


def _percentile(values, q):
    # canonical nearest-rank impl lives in obs/collector.py (the
    # /critical_path endpoint); deferred import keeps this module's
    # import cheap — by first call the engines imported jax anyway
    from dalle_pytorch_tpu.obs.collector import _percentile as impl

    return impl(values, q)


def _stage_snapshot(registry):
    """(sum, count) per stage label of the batcher's stage family — taken
    before a measured window so the breakdown excludes warmup and the
    saturation-calibration flood."""
    fam = registry.get("dalle_serving_stage_seconds")
    if fam is None:
        return {}
    return {label: (child.sum, child.count) for label, child in fam.items()}


def _stage_breakdown(registry, before):
    """Per-stage deltas since `before` as {stage: {mean_ms, count}} — the
    JSON-line view of where a request's wall time went (queue vs
    prefill/chunk/harvest vs the micro engine's generate)."""
    fam = registry.get("dalle_serving_stage_seconds")
    if fam is None:
        return {}
    out = {}
    for label, child in fam.items():
        s0, c0 = before.get(label, (0.0, 0))
        dc = child.count - c0
        if dc > 0:
            out[label] = {
                "mean_ms": round(1000.0 * (child.sum - s0) / dc, 3),
                "count": int(dc),
            }
    return out


def run_open_loop(batcher, text_ids, arrivals, seeds, timeout_s=120.0,
                  texts=None, tracer=None):
    """Replay a pre-drawn Poisson arrival schedule against one batcher.

    `arrivals` are offsets (seconds) from the run start; both engines see
    the identical schedule and per-request seeds, so "at the same Poisson
    arrival rate" is literal. `texts` optionally carries one prompt per
    arrival (the `--prompt_reuse` schedule); default is `text_ids` for
    every request. Returns sustained req/s (completions over the span from
    first submit to last completion) and TTFT percentiles from
    `GenRequest.first_token_at` (micro-batch: batch completion — its first
    token only exists once the full scan finishes; continuous: the first
    chunk boundary after admission). When the engine reports prefix-cache
    admissions (`GenRequest.prefix_hit`, paged engine only), the stats
    split TTFT by hit vs cold so the cache's win is measured on ONE run,
    not across runs.

    `tracer` (--trace_export) mints one client-side trace per arrival —
    the bench plays the fleet ingress role: its root span parents the
    batcher's queue/prefill/chunk/harvest spans, and finish() at
    completion ships the trace to the in-process collector, so the JSON
    line's `critical_path` block covers exactly the measured window.
    """
    from dalle_pytorch_tpu.obs.tracing import NULL_TRACE
    from dalle_pytorch_tpu.serving.engine import SampleSpec

    submitted, rejected = [], 0
    t_start = time.monotonic()
    for i, (offset, seed) in enumerate(zip(arrivals, seeds)):
        delay = t_start + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        ids = text_ids if texts is None else texts[i]
        trace = (
            tracer.start_trace("request", arrival=i) if tracer is not None
            else NULL_TRACE
        )
        try:
            req = batcher.submit(
                [SampleSpec(ids, seed=int(seed))], timeout_s=timeout_s,
                trace=trace,
            )
            submitted.append((time.monotonic(), req))
        except Exception:  # queue-full backpressure counts against the engine
            trace.finish("rejected")
            rejected += 1

    ttfts, errors = [], 0
    hit_ttfts, cold_ttfts, hit_known = [], [], 0
    last_done = time.monotonic()
    for t_submit, req in submitted:
        try:
            req.future.result(timeout=timeout_s)
        except Exception:
            req.trace.finish("error")
            errors += 1
            continue
        req.trace.finish("ok")
        last_done = max(last_done, time.monotonic())
        if req.first_token_at is not None:
            ttft = req.first_token_at - t_submit
            ttfts.append(ttft)
            if req.prefix_hit is not None:
                hit_known += 1
                (hit_ttfts if req.prefix_hit else cold_ttfts).append(ttft)
    # sustained rate over submit-to-last-completion: the queue backlog an
    # engine builds up during the arrival window is paid for, not free
    wall = last_done - t_start
    completed = len(submitted) - errors
    span = max(wall, 1e-9)
    out = {
        "offered": len(arrivals),
        "submitted": len(submitted),
        "rejected": rejected,
        "completed": completed,
        "errors": errors,
        "wall_s": round(wall, 3),
        "rps": round(completed / span, 3),
        "ttft_p50_ms": round(1000 * _percentile(ttfts, 0.5), 1) if ttfts else None,
        "ttft_p95_ms": round(1000 * _percentile(ttfts, 0.95), 1) if ttfts else None,
        "ttft_mean_ms": round(1000 * sum(ttfts) / len(ttfts), 1) if ttfts else None,
    }
    if hit_known:
        out["prefix_hit_rate"] = round(len(hit_ttfts) / hit_known, 3)
        if hit_ttfts:
            out["ttft_prefix_hit_p50_ms"] = round(
                1000 * _percentile(hit_ttfts, 0.5), 1
            )
            out["ttft_prefix_hit_mean_ms"] = round(
                1000 * sum(hit_ttfts) / len(hit_ttfts), 1
            )
        if cold_ttfts:
            out["ttft_cold_p50_ms"] = round(
                1000 * _percentile(cold_ttfts, 0.5), 1
            )
            out["ttft_cold_mean_ms"] = round(
                1000 * sum(cold_ttfts) / len(cold_ttfts), 1
            )
    return out


def draw_prompt_schedule(rng, n, text_seq, num_text_tokens, prompt_reuse,
                         pool_size=8):
    """One prompt per arrival: with probability `prompt_reuse`, a draw from
    a small popularity pool (Zipf-ish 1/rank weights — a few prompts take
    most of the repeat traffic, like prompt templates / n-samples fan-out
    in production mixes); otherwise a fresh unique prompt. 0 makes every
    prompt unique — deliberately cache-cold (the pre-paging bench repeated
    ONE prompt for every arrival, which would be a 100% prefix-hit
    workload)."""
    import numpy as np

    weights = 1.0 / np.arange(1, pool_size + 1)
    weights /= weights.sum()
    popular = [
        rng.integers(1, num_text_tokens, size=text_seq).astype(np.int32)
        for _ in range(pool_size)
    ]
    return [
        popular[rng.choice(pool_size, p=weights)]
        if prompt_reuse > 0 and rng.random() < prompt_reuse
        else rng.integers(1, num_text_tokens, size=text_seq).astype(np.int32)
        for _ in range(n)
    ]


def _sustained_rps(batcher, text_ids, seconds=2.5, clients=16,
                   make_text=None):
    """Closed-loop flood: measured saturation throughput of one batcher.

    More robust than timing a single scan — on a shared/noisy host a
    one-shot measurement can be off by 3x, and an open-loop rate derived
    from it lands past saturation, where the bench measures queue buildup
    instead of admission policy.

    `make_text(cid, i)` supplies a DISTINCT prompt per submission so a
    prefix-caching engine calibrates on the COLD admission path — one
    repeated prompt would measure the ~100% hit path and inflate the cap
    the open-loop rate derives from; None floods `text_ids`.
    """
    import threading as _th

    from dalle_pytorch_tpu.serving.engine import SampleSpec

    done = []
    stop = time.monotonic() + seconds
    lock = _th.Lock()

    def client(cid):
        i = 0
        while time.monotonic() < stop:
            ids = text_ids if make_text is None else make_text(cid, i)
            try:
                req = batcher.submit(
                    [SampleSpec(ids, seed=1_000_000 + cid * 10_000 + i)],
                    timeout_s=60.0,
                )
                req.future.result(timeout=60.0)
                with lock:
                    done.append(1)
            except Exception:
                time.sleep(0.01)  # backpressure: retry
            i += 1

    threads = [
        _th.Thread(target=client, args=(c,)) for c in range(clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return len(done) / max(time.monotonic() - t0, 1e-9)


def _kv_quality_block(model, micro, cont, n=4, label="quantized"):
    """CLIP-score parity of a degraded decode path, reported BESIDE the
    speed numbers: the same (prompt, seed) rows generate through the
    bf16 micro engine (the reference — a bf16 continuous engine is
    bit-identical to it by the composition-invariance contract) and the
    continuous engine under test, and one toy CLIP (fixed init) scores
    both image sets against their prompts. `clip_delta_mean` is
    `label` minus reference — ~0 means the variant paid no quality for
    its win (int8: ~2x capacity; policy sparsity: skipped KV tiles).
    Runs AFTER the measured window on already-warm programs; the
    token-agreement fraction is reported too (both variants are
    different numerical paths, so tokens MAY diverge — the CLIP delta
    is the acceptance metric, not token identity)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_pytorch_tpu.models.clip import CLIP, clip_scores
    from dalle_pytorch_tpu.serving.engine import SampleSpec

    n = max(1, min(n, cont.max_batch))
    rng = np.random.default_rng(1234)
    texts = rng.integers(
        1, model.num_text_tokens, size=(n, model.text_seq_len)
    ).astype(np.int32)
    specs = [SampleSpec(texts[i], seed=9000 + i) for i in range(n)]

    ref_toks, ref_px = micro.generate(specs)
    for i, sp in enumerate(specs):
        cont.prefill_slot(i, sp)
    for _ in range(4 * model.image_seq_len):
        pos, act = cont.step_chunk()
        if (pos[act] >= cont.image_seq_len).all():
            break
    q_toks = np.asarray(cont.harvest(list(range(n))))
    cont.release(list(range(n)))
    q_px = cont.decode_pixels(q_toks)

    image_size = int(np.asarray(ref_px).shape[1])
    clip = CLIP(
        dim_text=32, dim_image=32, dim_latent=16,
        num_text_tokens=model.num_text_tokens,
        text_enc_depth=1, text_seq_len=model.text_seq_len, text_heads=2,
        visual_enc_depth=1, visual_heads=2,
        visual_image_size=image_size,
        visual_patch_size=max(1, image_size // 4),
    )
    cv = clip.init(
        jax.random.PRNGKey(7), jnp.asarray(texts), jnp.asarray(ref_px)
    )
    ref_s = np.asarray(
        clip_scores(clip, cv, jnp.asarray(texts), jnp.asarray(ref_px))
    )
    q_s = np.asarray(
        clip_scores(clip, cv, jnp.asarray(texts), jnp.asarray(q_px))
    )
    return {
        "rows": int(n),
        "token_agreement": round(
            float((np.asarray(ref_toks)[:n] == q_toks[:n]).mean()), 4
        ),
        "clip_mean_ref": round(float(ref_s.mean()), 5),
        f"clip_mean_{label}": round(float(q_s.mean()), 5),
        "clip_delta_mean": round(float((q_s - ref_s).mean()), 5),
    }


def main_open_loop(prompt_reuse=0.0, kv_layout="slot", mesh=None,
                   trace_export=False, kv_dtype="model",
                   decode_sparsity="causal"):
    import jax
    import numpy as np

    from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher, MicroBatcher
    from dalle_pytorch_tpu.serving.engine import (
        ContinuousEngine, GenerationEngine, PagedContinuousEngine, SampleSpec,
    )
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    kv_dt = None if kv_dtype in (None, "model") else str(kv_dtype)
    sparse = decode_sparsity not in (None, "causal")

    # open-loop defaults use a LARGER toy than the closed-loop sweep
    # (dim 128 / depth 3 / 8x8 grid = 64 image tokens): on the tiny model
    # host dispatch overhead dominates decode compute, which is the
    # opposite of the regime continuous batching targets (a real
    # accelerator is decode-bound) and makes the comparison measure Python
    # loop costs instead of admission policy. Still overridable via env.
    os.environ.setdefault("SERVE_DIM", "128")
    os.environ.setdefault("SERVE_DEPTH", "3")
    os.environ.setdefault("SERVE_FMAP", "8")
    shapes = tuple(
        int(b) for b in os.environ.get("SERVE_BATCH_SHAPES", "1,4,8").split(",")
    )
    delay_ms = float(os.environ.get("SERVE_DELAY_MS", "25"))
    chunk_tokens = int(os.environ.get("SERVE_CHUNK_TOKENS", "8"))
    duration_s = float(os.environ.get("SERVE_OPEN_SECONDS", "10"))
    max_batch = max(shapes)

    model, params, vae, vae_params, text_ids = build_toy(sparse=sparse)

    micro = GenerationEngine(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        batch_shapes=shapes, registry=MetricsRegistry(),
    )
    micro.warmup()
    mb = MicroBatcher(
        micro, max_delay_ms=delay_ms,
        max_queue_rows=max(64, 4 * max_batch), registry=micro.registry,
    )

    prefill_batch = int(os.environ.get("SERVE_PREFILL_BATCH", "4"))
    page_size = int(os.environ.get("SERVE_PAGE_SIZE", "16"))
    cont_kw = dict(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        max_batch=max_batch, chunk_tokens=chunk_tokens,
        prefill_batch=prefill_batch, registry=MetricsRegistry(),
        kv_dtype=kv_dt,
        decode_sparsity="policy" if sparse else "causal",
    )
    if kv_layout == "paged":
        kv_pages_env = os.environ.get("SERVE_KV_PAGES")
        cont_kw.update(
            page_size=page_size,
            kv_pages=int(kv_pages_env) if kv_pages_env else None,
        )
        if mesh is not None:
            from dalle_pytorch_tpu.serving.sharded import (
                ShardedPagedContinuousEngine,
            )

            cont = ShardedPagedContinuousEngine(mesh_shape=mesh, **cont_kw)
        else:
            cont = PagedContinuousEngine(**cont_kw)
    elif mesh is not None:
        from dalle_pytorch_tpu.serving.sharded import ShardedContinuousEngine

        cont = ShardedContinuousEngine(mesh_shape=mesh, **cont_kw)
    else:
        cont = ContinuousEngine(**cont_kw)
    # per-program cost capture (obs/vitals.py) before warmup so the
    # continuous line can report live MFU over the measured window
    from dalle_pytorch_tpu.obs import EngineVitals, ProgramCostTable

    cont.cost_table = ProgramCostTable(registry=cont.registry)
    cont.warmup()
    cb = ContinuousBatcher(
        cont, max_queue_rows=max(64, 4 * max_batch), registry=cont.registry,
    )

    # offered load: ~40% of the SLOWER engine's measured saturation
    # throughput — loaded enough that the micro engine must coalesce
    # several rows per flush (arrivals genuinely wait behind in-flight
    # scans), with enough margin that neither engine crosses into
    # saturation even if the host slows down between calibration and run
    # (past saturation the bench measures queue buildup, not admission
    # policy). Override with SERVE_RATE_RPS to sweep the load axis.
    def _unique_text(cid, i):
        # distinct per submission: both caps measure COLD admissions, so
        # they stay comparable across --kv_layout runs (a repeated prompt
        # would calibrate the paged engine on its ~100% prefix-hit path)
        r = np.random.default_rng([cid, i])
        return r.integers(
            1, model.num_text_tokens, size=model.text_seq_len
        ).astype(np.int32)

    micro_cap = _sustained_rps(mb, text_ids, make_text=_unique_text)
    cont_cap = _sustained_rps(cb, text_ids, make_text=_unique_text)
    rate = float(
        os.environ.get("SERVE_RATE_RPS", 0.4 * min(micro_cap, cont_cap))
    )

    rng = np.random.default_rng(int(os.environ.get("SERVE_ARRIVAL_SEED", "0")))
    gaps = rng.exponential(1.0 / rate, size=int(rate * duration_s) + 1)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration_s]
    seeds = rng.integers(0, 2**31 - 1, size=len(arrivals))
    # one prompt per arrival, IDENTICAL for both engines — with
    # --prompt_reuse > 0 repeat prompts hit the paged engine's prefix cache
    # while the micro/slotted path pays a full prefill either way, so the
    # hit-vs-cold TTFT split isolates the cache's win on one schedule
    texts = draw_prompt_schedule(
        rng, len(arrivals), model.text_seq_len, model.num_text_tokens,
        prompt_reuse,
    )

    common = {
        "metric": "serving_openloop_rps",
        "unit": UNIT,
        "device": jax.devices()[0].platform,
        "mode": "open-loop",
        "rate_rps": round(rate, 3),
        "duration_s": duration_s,
        "batch_shapes": list(shapes),
        "prompt_reuse": prompt_reuse,
        "micro_saturation_rps": round(micro_cap, 3),
        "continuous_saturation_rps": round(cont_cap, 3),
    }

    # --trace_export: an in-process collector (real HTTP on port 0) plus
    # one tracer+exporter per engine run — the bench exercises the SAME
    # export path a fleet replica uses, and each line's `critical_path`
    # block folds exactly the traces of its measured window (tracers are
    # created after calibration; the collector resets between engines)
    collector_srv = None
    if trace_export:
        from dalle_pytorch_tpu.obs import CollectorServer, TraceExporter, Tracer

        collector_srv = CollectorServer(grace_s=0.05).start()

    def _traced_run(batcher, site, **kw):
        """One open-loop replay, optionally traced+exported; returns
        (stats, critical_path block or None)."""
        if collector_srv is None:
            return run_open_loop(batcher, text_ids, arrivals, seeds, **kw), None
        tracer = Tracer(max_traces=len(arrivals) + 8)
        exporter = TraceExporter(collector_srv.url, site=site).attach(tracer)
        stats = run_open_loop(
            batcher, text_ids, arrivals, seeds, tracer=tracer, **kw
        )
        exporter.flush()
        exporter.stop(final_flush=False)
        block = collector_srv.collector.critical_path()
        collector_srv.collector.reset()
        return stats, block

    micro_stages0 = _stage_snapshot(micro.registry)
    micro_stats, micro_cp = _traced_run(mb, "bench-micro", texts=texts)
    mb.shutdown(drain=True)
    micro_line = {
        **common, "engine": "micro", "value": micro_stats["rps"],
        "max_delay_ms": delay_ms, **micro_stats,
        "stages": _stage_breakdown(micro.registry, micro_stages0),
    }
    if micro_cp is not None:
        micro_line["critical_path"] = micro_cp
    print(json.dumps(micro_line), flush=True)

    # admission-dispatch accounting: how well batched prefill amortized the
    # per-row admission cost over the MEASURED window (warmup is excluded by
    # the engine's counter tagging; the saturation-calibration flood is
    # excluded by snapshotting here). rows/dispatch == prefill_batch means
    # every wave ran full; 1.0 means arrivals were too sparse to coalesce.
    pf_rows0 = cont.registry.get("dalle_serving_prefills_total").value
    pf_disp0 = cont.registry.get(
        "dalle_serving_prefill_dispatches_total"
    ).value
    # sparsity tile accounting, windowed like the prefill counters
    tiles_read0 = cont.registry.get(
        "dalle_serving_kv_tiles_read_total"
    ).value
    tiles_skip0 = cont.registry.get(
        "dalle_serving_kv_tiles_skipped_total"
    ).value
    cont_stages0 = _stage_snapshot(cont.registry)
    # vitals sampled over the MEASURED window only: the ring starts empty
    # here (after calibration), stops before the JSON line renders
    vitals = EngineVitals(interval_s=0.05, max_samples=4096)
    vitals.bind(engine=cont, batcher=cb)
    vitals.start()
    if kv_layout == "paged":
        # measured-window occupancy: the saturation-calibration flood above
        # already pushed the pool to ITS peak, so restart the watermark (and
        # hit/miss tallies) at the live level before the schedule replays
        cont.kv.pool.peak_allocated = cont.kv.pool.n_allocated
        hits0, misses0 = cont.kv.cache.hits, cont.kv.cache.misses
        evictions0 = cont.kv.cache.evictions
    cont_stats, cont_cp = _traced_run(cb, "bench-continuous", texts=texts)
    vitals.stop()
    cb.shutdown(drain=True)
    # mean/peak occupancy + per-program MFU over the measured window
    vitals_block = vitals.window_summary()
    mfu = {
        row["program"]: row["mfu"]
        for row in cont.cost_table.rows()
        if row.get("mfu") is not None
    }
    if mfu:
        vitals_block["mfu"] = mfu
    pf_rows = (
        cont.registry.get("dalle_serving_prefills_total").value - pf_rows0
    )
    pf_disp = (
        cont.registry.get("dalle_serving_prefill_dispatches_total").value
        - pf_disp0
    )
    cont_line = {
        **common, "engine": "continuous", "value": cont_stats["rps"],
        "kv_layout": kv_layout,
        "kv_dtype": kv_dt or "model",
        "kv_bytes_per_slot": int(cont.kv_bytes_per_slot()),
        "chunk_tokens": chunk_tokens,
        "prefill_batch": cont.prefill_batch,
        "prefill_rows": int(pf_rows),
        "prefill_dispatches": int(pf_disp),
        "prefill_rows_per_dispatch": (
            round(pf_rows / pf_disp, 2) if pf_disp else None
        ),
        **cont_stats,
        "stages": _stage_breakdown(cont.registry, cont_stages0),
        "vitals": vitals_block,
    }
    if cont_cp is not None:
        cont_line["critical_path"] = cont_cp
    if mesh is not None:
        # mesh shape + per-device memory PEAK over the measured window
        # (from the sampler's per-device memory_stats; empty on backends
        # without memory stats — the live state-buffer split from
        # mesh_detail still names each shard's share)
        peaks = {}
        for snap in vitals.recent():
            for dev, stats in (
                snap.get("memory_stats_per_device") or {}
            ).items():
                peaks[dev] = max(
                    peaks.get(dev, 0), stats.get("bytes_in_use", 0)
                )
        cont_line["mesh"] = {
            **cont.mesh_detail(),
            "per_device_peak_bytes": peaks,
        }
    if kv_layout == "paged":
        # HBM story: pages the measured window ACTUALLY peaked at vs the
        # slotted layout's always-resident worst case (max_batch full-length
        # lanes). peak_fraction_of_slotted < 1.0 is the paged win — cache
        # positions the slotted engine pins but this run never touched.
        slotted_pages = cont.max_batch * cont.kv.pages_per_row
        cache = cont.kv.cache
        cont_line["block_occupancy"] = {
            "page_size": cont.page_size,
            "pages_total": cont.kv.pool.n_pages - 1,
            "pages_peak": int(cont.kv.pool.peak_allocated),
            "pages_slotted_equiv": slotted_pages,
            "peak_fraction_of_slotted": round(
                cont.kv.pool.peak_allocated / slotted_pages, 3
            ),
        }
        window_hits = cache.hits - hits0
        window_misses = cache.misses - misses0
        admitted = window_hits + window_misses
        cont_line["prefix_cache"] = {
            "entries": len(cache),
            "hits": int(window_hits),
            "misses": int(window_misses),
            "hit_rate": round(window_hits / admitted, 3) if admitted else None,
            # windowed like hits/misses: the saturation-calibration flood
            # can evict against a capped pool before the schedule replays
            "evictions": int(cache.evictions - evictions0),
        }
    if sparse:
        # per-line tile accounting over the measured window: skipped > 0
        # is the policy actually buying DMA/compute (vs length skip
        # alone), read gives the denominator for the skip fraction
        tiles_read = int(
            cont.registry.get("dalle_serving_kv_tiles_read_total").value
            - tiles_read0
        )
        tiles_skip = int(
            cont.registry.get("dalle_serving_kv_tiles_skipped_total").value
            - tiles_skip0
        )
        cont_line["decode_sparsity"] = "policy"
        cont_line["kv_tiles_read"] = tiles_read
        cont_line["kv_tiles_skipped"] = tiles_skip
        total = tiles_read + tiles_skip
        cont_line["kv_tile_skip_fraction"] = (
            round(tiles_skip / total, 4) if total else None
        )
        sp_detail = cont.sparsity_detail() or {}
        cont_line["sparsity"] = {
            k: sp_detail[k]
            for k in (
                "block", "n_blocks", "patterned_layers",
                "static_dead_tile_frac",
            )
            if k in sp_detail
        }
    if kv_dt is not None or sparse:
        # quality beside speed: the degraded decode path's CLIP-score
        # cost on the SAME (prompt, seed) rows, scored against the bf16
        # micro engine's output (bit-identical to a bf16 continuous
        # engine by the composition-invariance contract; the micro
        # engine decodes patterned layers through the dense masked path,
        # so for sparse runs it doubles as the exact-mask oracle)
        cont_line["quality"] = _kv_quality_block(
            model, micro, cont,
            label="sparse" if kv_dt is None else "quantized",
        )
    if micro_stats["rps"]:
        cont_line["rps_ratio_vs_micro"] = round(
            cont_stats["rps"] / micro_stats["rps"], 3
        )
    if micro_stats["ttft_p95_ms"] and cont_stats["ttft_p95_ms"]:
        cont_line["ttft_p95_ratio_vs_micro"] = round(
            cont_stats["ttft_p95_ms"] / micro_stats["ttft_p95_ms"], 3
        )
    print(json.dumps(cont_line), flush=True)
    if collector_srv is not None:
        collector_srv.shutdown()


def run_stream_open_loop(batcher, arrivals, seeds, texts, timeout_s=120.0):
    """Replay a Poisson schedule with a live event stream per request.

    Every submit carries a `RequestStream` (the same object the SSE
    handler hangs a client on), so the batcher's chunk-boundary callback
    emits progress events and — every `preview_every` chunks — pays the
    snapshot + preview fill-decode dispatch. TTFP (time-to-first-preview)
    is stamped the moment `preview()` lands the event in the ring: that
    is when an attached SSE reader would wake, so it times exactly what a
    streaming client sees minus PNG encoding (the server's cost, not the
    engine's). Returns TTFT percentiles like `run_open_loop` plus
    ttfp_p50/p95/mean and per-stream event accounting.
    """
    from dalle_pytorch_tpu.serving.engine import SampleSpec
    from dalle_pytorch_tpu.serving.streaming import RequestStream

    class _TimedStream(RequestStream):
        # bench-side stamps: the batcher worker calls progress()/preview()
        # at chunk boundaries, so monotonic-on-emit is reader-visible time
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.first_progress_at = None
            self.first_preview_at = None

        def progress(self, chunk, **data):
            ok = super().progress(chunk, **data)
            if ok and self.first_progress_at is None:
                self.first_progress_at = time.monotonic()
            return ok

        def preview(self, chunk, **data):
            ok = super().preview(chunk, **data)
            if ok and self.first_preview_at is None:
                self.first_preview_at = time.monotonic()
            return ok

    submitted, rejected = [], 0
    t_start = time.monotonic()
    for i, (offset, seed) in enumerate(zip(arrivals, seeds)):
        delay = t_start + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        stream = _TimedStream(key=f"bench-stream-{i}")
        try:
            req = batcher.submit(
                [SampleSpec(texts[i], seed=int(seed))], timeout_s=timeout_s,
                stream=stream,
            )
            submitted.append((time.monotonic(), req, stream))
        except Exception:  # queue-full backpressure counts against the engine
            rejected += 1

    ttfts, ttfps, errors = [], [], 0
    previews_total = progress_total = 0
    last_done = time.monotonic()
    for t_submit, req, stream in submitted:
        try:
            req.future.result(timeout=timeout_s)
        except Exception:
            errors += 1
            continue
        last_done = max(last_done, time.monotonic())
        if req.first_token_at is not None:
            ttfts.append(req.first_token_at - t_submit)
        if stream.first_preview_at is not None:
            ttfps.append(stream.first_preview_at - t_submit)
        previews_total += stream.previews_sent
        progress_total += stream.events_emitted - stream.previews_sent
    wall = last_done - t_start
    completed = len(submitted) - errors
    span = max(wall, 1e-9)
    return {
        "offered": len(arrivals),
        "submitted": len(submitted),
        "rejected": rejected,
        "completed": completed,
        "errors": errors,
        "wall_s": round(wall, 3),
        "rps": round(completed / span, 3),
        "ttft_p50_ms": round(1000 * _percentile(ttfts, 0.5), 1) if ttfts else None,
        "ttft_p95_ms": round(1000 * _percentile(ttfts, 0.95), 1) if ttfts else None,
        "ttfp_p50_ms": round(1000 * _percentile(ttfps, 0.5), 1) if ttfps else None,
        "ttfp_p95_ms": round(1000 * _percentile(ttfps, 0.95), 1) if ttfps else None,
        "ttfp_mean_ms": round(1000 * sum(ttfps) / len(ttfps), 1) if ttfps else None,
        "streams_with_preview": len(ttfps),
        "previews_total": int(previews_total),
        "progress_events_total": int(progress_total),
    }


def main_stream_bench(kv_layout="slot"):
    """`--stream`: the streaming-previews acceptance instrument.

    One continuous engine with the preview fill-decode program warmed
    (`preview_enabled=True`), one open-loop Poisson replay where every
    request carries a live event stream. The headline is p95 TTFP in
    chunk periods (`ttfp_p95_chunk_periods`): a preview is one snapshot +
    one extra compiled dispatch at a chunk boundary, so time-to-first-
    pixels should sit within ~2 chunk periods of admission — the
    acceptance bound — while full-image TTFT is the whole decode away.
    SERVE_PREVIEW_EVERY (default 1) sets the preview cadence;
    SERVE_STREAM_SECONDS (default 8) the window.
    """
    import jax
    import numpy as np

    from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
    from dalle_pytorch_tpu.serving.engine import (
        ContinuousEngine, PagedContinuousEngine,
    )
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    os.environ.setdefault("SERVE_DIM", "128")
    os.environ.setdefault("SERVE_DEPTH", "3")
    os.environ.setdefault("SERVE_FMAP", "8")
    shapes = tuple(
        int(b) for b in os.environ.get("SERVE_BATCH_SHAPES", "1,4,8").split(",")
    )
    chunk_tokens = int(os.environ.get("SERVE_CHUNK_TOKENS", "8"))
    duration_s = float(os.environ.get("SERVE_STREAM_SECONDS", "8"))
    preview_every = int(os.environ.get("SERVE_PREVIEW_EVERY", "1"))
    prefill_batch = int(os.environ.get("SERVE_PREFILL_BATCH", "4"))
    max_batch = max(shapes)

    model, params, vae, vae_params, text_ids = build_toy()
    engine_kw = dict(
        model=model, variables=params, vae=vae, vae_params=vae_params,
        max_batch=max_batch, chunk_tokens=chunk_tokens,
        prefill_batch=prefill_batch, registry=MetricsRegistry(),
        preview_enabled=True,
    )
    if kv_layout == "paged":
        cont = PagedContinuousEngine(
            page_size=int(os.environ.get("SERVE_PAGE_SIZE", "16")),
            **engine_kw,
        )
    else:
        cont = ContinuousEngine(**engine_kw)
    from dalle_pytorch_tpu.obs import ProgramCostTable

    cont.cost_table = ProgramCostTable(registry=cont.registry)
    cont.warmup()
    cb = ContinuousBatcher(
        cont, max_queue_rows=max(64, 4 * max_batch), registry=cont.registry,
        preview_every=preview_every,
    )

    def _unique_text(cid, i):
        r = np.random.default_rng([cid, i])
        return r.integers(
            1, model.num_text_tokens, size=model.text_seq_len
        ).astype(np.int32)

    cap = _sustained_rps(cb, text_ids, make_text=_unique_text)
    rate = float(os.environ.get("SERVE_RATE_RPS", 0.4 * cap))
    rng = np.random.default_rng(int(os.environ.get("SERVE_ARRIVAL_SEED", "0")))
    gaps = rng.exponential(1.0 / rate, size=int(rate * duration_s) + 1)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration_s]
    seeds = rng.integers(0, 2**31 - 1, size=len(arrivals))
    texts = draw_prompt_schedule(
        rng, len(arrivals), model.text_seq_len, model.num_text_tokens, 0.0,
    )

    stages0 = _stage_snapshot(cont.registry)
    stats = run_stream_open_loop(cb, arrivals, seeds, texts)
    cb.shutdown(drain=True)
    stages = _stage_breakdown(cont.registry, stages0)
    line = {
        "metric": "serving_stream_ttfp",
        "unit": "ms",
        "device": jax.devices()[0].platform,
        "mode": "stream",
        "engine": "continuous",
        "kv_layout": kv_layout,
        "value": stats["ttfp_p95_ms"],
        "rate_rps": round(rate, 3),
        "duration_s": duration_s,
        "chunk_tokens": chunk_tokens,
        "preview_every": preview_every,
        "continuous_saturation_rps": round(cap, 3),
        **stats,
        "stream_events": _class_counter_values(
            cont.registry, "dalle_serving_stream_events_total"
        ),
        "stages": stages,
    }
    # the acceptance bound: first preview within ~2 chunk periods of the
    # request's first decode work (one period to REACH a boundary, one for
    # the snapshot + preview dispatch riding it); the chunk period is
    # measured from this window's own stage breakdown
    chunk_ms = (stages.get("chunk") or {}).get("mean_ms")
    if chunk_ms and stats["ttfp_p95_ms"] and stats["ttft_p95_ms"]:
        line["chunk_period_ms"] = chunk_ms
        # queueing + prefill delay is TTFT-side, common to both numbers;
        # the preview machinery's own cost is the gap between first
        # preview and first token, which is what the bound polices
        ttfp_over_ttft_ms = stats["ttfp_p95_ms"] - stats["ttft_p95_ms"]
        line["ttfp_p95_minus_ttft_p95_ms"] = round(ttfp_over_ttft_ms, 1)
        line["ttfp_p95_chunk_periods"] = round(
            max(ttfp_over_ttft_ms, 0.0) / chunk_ms, 2
        )
    print(json.dumps(line), flush=True)


def _class_counter_values(registry, name):
    """{label: value} of a counter family (empty when never registered)."""
    fam = registry.get(name)
    if fam is None:
        return {}
    return {label: int(child.value) for label, child in fam.items()}


def _ttft_stats(ttfts):
    if not ttfts:
        return {"ttft_p50_ms": None, "ttft_p95_ms": None}
    return {
        "ttft_p50_ms": round(1000 * _percentile(ttfts, 0.5), 1),
        "ttft_p95_ms": round(1000 * _percentile(ttfts, 0.95), 1),
    }


def run_priority_open_loop(batcher, arrivals, seeds, texts, priorities,
                           timeout_s):
    """Replay a Poisson schedule with per-arrival priority classes.

    Returns {class: stats} with offered/shed/rejected/completed counts
    and TTFT percentiles per class. Sheds (`ShedError`) and queue-full
    rejects are counted separately: under deliberate overload both are
    CORRECT behavior for the low class, and the bench line must show
    which mechanism absorbed the excess."""
    from dalle_pytorch_tpu.serving.engine import SampleSpec
    from dalle_pytorch_tpu.serving.qos import ShedError, TenantQuotaError

    per_class = {
        c: {"offered": 0, "shed": 0, "rejected": 0, "errors": 0,
            "completed": 0, "ttfts": []}
        for c in set(priorities)
    }
    submitted = []
    t_start = time.monotonic()
    for i, (offset, seed) in enumerate(zip(arrivals, seeds)):
        delay = t_start + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        cls = priorities[i]
        stats = per_class[cls]
        stats["offered"] += 1
        try:
            req = batcher.submit(
                [SampleSpec(texts[i], seed=int(seed))],
                timeout_s=timeout_s, priority=cls,
            )
            submitted.append((time.monotonic(), cls, req))
        except (ShedError, TenantQuotaError):
            stats["shed"] += 1
        except Exception:
            stats["rejected"] += 1
    for t_submit, cls, req in submitted:
        stats = per_class[cls]
        try:
            req.future.result(timeout=timeout_s + 30.0)
        except Exception:
            stats["errors"] += 1
            continue
        stats["completed"] += 1
        if req.first_token_at is not None:
            stats["ttfts"].append(req.first_token_at - t_submit)
    out = {}
    for cls, stats in per_class.items():
        ttfts = stats.pop("ttfts")
        out[cls] = {**stats, **_ttft_stats(ttfts)}
    return out


def main_priority_mix(mix, kv_layout="slot", prompt_reuse=0.0):
    """`--priority_mix`: QoS under deliberate overload, one JSON line."""
    import jax
    import numpy as np

    from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
    from dalle_pytorch_tpu.serving.engine import (
        ContinuousEngine, PagedContinuousEngine, SampleSpec,
    )
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    assert 0.0 < mix < 1.0, "--priority_mix is the HIGH-class fraction"
    os.environ.setdefault("SERVE_DIM", "128")
    os.environ.setdefault("SERVE_DEPTH", "3")
    os.environ.setdefault("SERVE_FMAP", "8")
    shapes = tuple(
        int(b) for b in os.environ.get("SERVE_BATCH_SHAPES", "1,4,8").split(",")
    )
    max_batch = max(shapes)
    chunk_tokens = int(os.environ.get("SERVE_CHUNK_TOKENS", "8"))
    duration_s = float(os.environ.get("SERVE_OPEN_SECONDS", "10"))
    overload = float(os.environ.get("SERVE_PRIORITY_OVERLOAD", "1.3"))
    timeout_s = float(os.environ.get("SERVE_PRIORITY_TIMEOUT", "30"))

    model, params, vae, vae_params, text_ids = build_toy()
    prefill_batch = int(os.environ.get("SERVE_PREFILL_BATCH", "4"))
    if kv_layout == "paged":
        kv_pages_env = os.environ.get("SERVE_KV_PAGES")
        cont = PagedContinuousEngine(
            model=model, variables=params, vae=vae, vae_params=vae_params,
            max_batch=max_batch, chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch, registry=MetricsRegistry(),
            page_size=int(os.environ.get("SERVE_PAGE_SIZE", "16")),
            kv_pages=int(kv_pages_env) if kv_pages_env else None,
        )
    else:
        cont = ContinuousEngine(
            model=model, variables=params, vae=vae, vae_params=vae_params,
            max_batch=max_batch, chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch, registry=MetricsRegistry(),
        )
    cont.warmup()
    # one slot held for the high class (SERVE_PRIORITY_RESERVE): a high
    # arrival then admits at the next chunk boundary without waiting for
    # a preemption cycle — the config the QoS acceptance ratio is stated
    # for (preemption alone still bounds the tail, just one boundary
    # later; set 0 to measure the fully work-conserving policy)
    reserve = int(os.environ.get("SERVE_PRIORITY_RESERVE", "1"))
    cb = ContinuousBatcher(
        cont, max_queue_rows=max(64, 4 * max_batch), registry=cont.registry,
        preempt=True, deadline_shed=True,
        reserve_slots=min(reserve, max_batch - 1),
    )

    cap = _sustained_rps(
        cb, text_ids,
        make_text=lambda cid, i: np.random.default_rng([cid, i]).integers(
            1, model.num_text_tokens, size=model.text_seq_len
        ).astype(np.int32),
    )
    rate = float(os.environ.get("SERVE_RATE_RPS", 0) or overload * cap)

    rng = np.random.default_rng(int(os.environ.get("SERVE_ARRIVAL_SEED", "0")))

    # unloaded high-priority baseline: the SAME Poisson arrival process
    # at a light rate (default 15% of saturation), all high class — the
    # denominator of the acceptance ratio. Open-loop, not sequential-idle
    # probing: an idle probe always catches the worker parked and
    # measures the best case, while every real arrival pays the
    # mid-chunk admission wait — the ratio must compare like with like.
    base_frac = float(os.environ.get("SERVE_PRIORITY_BASELINE_FRACTION",
                                     "0.15"))
    base_rate = max(base_frac * cap, 1.0)
    base_dur = min(duration_s, 5.0)
    base_gaps = rng.exponential(1.0 / base_rate,
                                size=int(base_rate * base_dur) + 1)
    base_arrivals = np.cumsum(base_gaps)
    base_arrivals = base_arrivals[base_arrivals < base_dur]
    base_seeds = rng.integers(0, 2**31 - 1, size=len(base_arrivals))
    base_texts = draw_prompt_schedule(
        rng, len(base_arrivals), model.text_seq_len, model.num_text_tokens,
        prompt_reuse,
    )
    unloaded = run_priority_open_loop(
        cb, base_arrivals, base_seeds, base_texts,
        ["high"] * len(base_arrivals), timeout_s,
    )["high"]
    gaps = rng.exponential(1.0 / rate, size=int(rate * duration_s) + 1)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration_s]
    seeds = rng.integers(0, 2**31 - 1, size=len(arrivals))
    texts = draw_prompt_schedule(
        rng, len(arrivals), model.text_seq_len, model.num_text_tokens,
        prompt_reuse,
    )
    priorities = [
        "high" if rng.random() < mix else "low" for _ in arrivals
    ]

    # counter snapshots so the line reports the measured window only
    pre = {
        name: _class_counter_values(cont.registry, f"dalle_serving_{name}")
        for name in ("preemptions_total", "resumptions_total", "shed_total")
    }
    classes = run_priority_open_loop(
        cb, arrivals, seeds, texts, priorities, timeout_s
    )
    cb.shutdown(drain=True)

    def window(name):
        now = _class_counter_values(cont.registry, f"dalle_serving_{name}")
        return {
            label: now.get(label, 0) - pre[name].get(label, 0)
            for label in now
        }

    line = {
        "metric": "serving_priority_mix",
        "unit": "ratio",
        "device": jax.devices()[0].platform,
        "mode": "open-loop",
        "engine": "continuous",
        "kv_layout": kv_layout,
        "priority_mix": mix,
        "rate_rps": round(rate, 3),
        "saturation_rps": round(cap, 3),
        "overload_factor": overload,
        "duration_s": duration_s,
        "request_timeout_s": timeout_s,
        "ttft_unloaded_p50_ms": unloaded["ttft_p50_ms"],
        "ttft_unloaded_p95_ms": unloaded["ttft_p95_ms"],
        "classes": classes,
        "preemptions": window("preemptions_total"),
        "resumptions": window("resumptions_total"),
        "shed": window("shed_total"),
        "dispatch_retries": int(
            cont.registry.get("dalle_serving_dispatch_retries_total").value
        ),
    }
    high = classes.get("high") or {}
    if high.get("ttft_p95_ms") and unloaded["ttft_p95_ms"]:
        line["high_ttft_p95_ratio_vs_unloaded"] = round(
            high["ttft_p95_ms"] / unloaded["ttft_p95_ms"], 3
        )
        line["value"] = line["high_ttft_p95_ratio_vs_unloaded"]
    else:
        line["value"] = None
    low = classes.get("low") or {}
    if high.get("ttft_p95_ms") and low.get("ttft_p95_ms"):
        line["low_ttft_p95_ratio_vs_high"] = round(
            low["ttft_p95_ms"] / high["ttft_p95_ms"], 3
        )
    print(json.dumps(line), flush=True)


def fleet_request(port, body, timeout=30.0, headers=None):
    """One HTTP POST /generate against the router. NEVER raises: a
    router-down window must record an error outcome in the load loop,
    not crash the bench (tests/test_router.py pins this)."""
    import urllib.error
    import urllib.request

    t0 = time.monotonic()
    out = {"ok": False, "status": None, "error": None, "payload": None}
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out["status"] = resp.status
            out["payload"] = json.loads(resp.read())
            out["ok"] = resp.status == 200
    except urllib.error.HTTPError as exc:
        out["status"] = exc.code
        out["error"] = f"http {exc.code}"
        try:
            exc.read()
        except Exception:
            pass
    except Exception as exc:
        out["error"] = repr(exc)
    out["latency_s"] = time.monotonic() - t0
    return out


def run_fleet_window(port, arrivals, seeds, timeout_s=60.0, on_offset=None,
                     tenant_of=None):
    """Open-loop Poisson replay through the router over HTTP: each
    arrival fires a client thread (open-loop — a slow fleet cannot slow
    the arrival process). `on_offset` is the chaos hook: (offset_s,
    callable) runs once when the schedule passes that offset — the bench
    kills a replica with it mid-window. `tenant_of` (index -> tenant
    string) stamps each request with a tenant so the usage ledger has
    something to attribute. Returns completion counts and latency
    percentiles."""
    results = [None] * len(arrivals)
    threads = []
    fired = threading.Event()
    t_start = time.monotonic()
    for i, (offset, seed) in enumerate(zip(arrivals, seeds)):
        delay = t_start + offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        if (
            on_offset is not None and not fired.is_set()
            and offset >= on_offset[0]
        ):
            fired.set()
            # off the arrival thread: a blocking kill (server shutdown
            # joins worker threads) must not stall the Poisson schedule
            threading.Thread(target=on_offset[1], daemon=True).start()

        def client(i=i, seed=seed):
            body = {"prompt": f"fleet bench {seed}", "seed": int(seed),
                    "timeout_s": timeout_s}
            if tenant_of is not None:
                body["tenant"] = tenant_of(i)
            results[i] = fleet_request(port, body, timeout=timeout_s + 5.0)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=timeout_s + 10.0)
    done = [r for r in results if r is not None]
    lat = sorted(r["latency_s"] for r in done if r["ok"])
    completed = sum(1 for r in done if r["ok"])
    wall = time.monotonic() - t_start
    return {
        "offered": len(arrivals),
        "completed": completed,
        "errors": len(arrivals) - completed,
        "wall_s": round(wall, 3),
        "rps": round(completed / max(wall, 1e-9), 3),
        "latency_p50_ms": (
            round(1000 * _percentile(lat, 0.5), 1) if lat else None
        ),
        "latency_p95_ms": (
            round(1000 * _percentile(lat, 0.95), 1) if lat else None
        ),
    }


def _fleet_block(scraper, router):
    """The telemetry-plane slice of the fleet bench line: one final
    scrape sweep (the killed replica shows up stale), then the capacity
    model's goodput/suggested-replicas read and the usage ledger's
    per-tenant chip-second attribution."""
    scraper.scrape_once()
    cap = scraper.capacity_report()
    usage = router.usage.summary()
    return {
        "goodput_fraction": cap["goodput"]["fraction"],
        "wasted_tokens": cap["goodput"]["wasted_tokens"],
        "suggested_replicas": cap["suggested_replicas"],
        "fresh_replicas": cap["fresh_replicas"],
        "scrape_generations": {
            name: {"generation": s.generation, "stale": s.stale}
            for name, s in sorted(scraper.snapshot().items())
        },
        "chip_seconds_by_tenant": {
            f'{r["tenant"]}/{r["priority"]}': r["chip_seconds"]
            for r in usage["tenants"]
        },
        "chip_seconds_total": usage["totals"]["chip_seconds"],
    }


def main_fleet(n_replicas, hedge_after_ms=None):
    """`--replicas N` fleet mode: N in-process continuous-engine
    replicas behind a real `FleetRouter`, open-loop load over HTTP, one
    replica HARD-KILLED mid-window — one JSON line with the healthy
    window, the chaos window (must still complete 100%), and the
    router's failover/hedge accounting."""
    import numpy as np

    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
    from dalle_pytorch_tpu.obs.fleetmetrics import FleetScraper
    from dalle_pytorch_tpu.serving.engine import ContinuousEngine
    from dalle_pytorch_tpu.serving.router import FleetRouter, RouterServer
    from dalle_pytorch_tpu.serving.server import ServingServer
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    assert n_replicas >= 2, "--replicas needs >= 2 (one gets killed)"
    chunk_tokens = int(os.environ.get("SERVE_CHUNK_TOKENS", "4"))
    max_batch = int(os.environ.get("SERVE_FLEET_SLOTS", "4"))
    duration_s = float(os.environ.get("SERVE_FLEET_SECONDS", "6"))
    model, params, vae, vae_params, _text_ids = build_toy()

    servers = []
    for _ in range(n_replicas):
        eng = ContinuousEngine(
            model=model, variables=params, vae=vae, vae_params=vae_params,
            max_batch=max_batch, chunk_tokens=chunk_tokens,
            prefill_batch=max_batch, registry=MetricsRegistry(),
        )
        eng.tokenizer = ByteTokenizer()
        servers.append(
            ServingServer(
                eng, port=0, request_timeout_s=120,
                max_queue_rows=max(64, 8 * max_batch),
            ).start()
        )
    router = FleetRouter(
        [f"r{i}=http://127.0.0.1:{s.port}" for i, s in enumerate(servers)],
        registry=MetricsRegistry(),
        hedge_after_ms=hedge_after_ms,
        probe_interval_s=0.25,
    )
    scraper = FleetScraper(
        [(rep.name, rep.url) for rep in router.replicas],
        registry=router.registry, usage=router.usage, interval_s=0.5,
    )
    front = RouterServer(router, port=0, fleet=scraper).start()
    port = front.port

    # warm every replica (compile + one real request) and calibrate the
    # offered rate off the measured warm latency: ~40% of the fleet's
    # rough capacity (max_batch rows per image-time per replica)
    warm_lat = []
    for i in range(n_replicas * 3):
        out = fleet_request(port, {"prompt": "warm", "seed": 10_000 + i})
        assert out["ok"], f"warmup request failed: {out}"
        warm_lat.append(out["latency_s"])
    # rate off the WARM single-request latency (last round only — the
    # first pays compiles), derated to 25% of the optimistic
    # slots-per-image-time fleet capacity: this is a ROBUSTNESS
    # instrument, so the healthy window must complete 100% and the chaos
    # claim isolates the kill, not queue-full backpressure
    image_s = max(min(warm_lat[-n_replicas:]), 1e-3)
    rate = 0.25 * n_replicas * max_batch / image_s
    rate = float(os.environ.get("SERVE_FLEET_RPS", rate))

    rng = np.random.default_rng(int(os.environ.get("SERVE_ARRIVAL_SEED", "0")))
    n = max(4, int(rate * duration_s))
    arrivals = np.sort(rng.uniform(0.0, duration_s, size=n))
    seeds = rng.integers(0, 2**31 - 1, size=n)

    reg = router.registry

    def _fam(name):
        fam = reg.get(name)
        if fam is None:
            return {}
        if hasattr(fam, "items"):
            return {label: int(c.value) for label, c in fam.items()}
        return {"total": int(fam.value)}

    # alternate two tenants so the usage ledger's chip-second
    # attribution has something to split
    tenant_of = lambda i: "tenant-a" if i % 2 == 0 else "tenant-b"

    healthy = run_fleet_window(port, arrivals, seeds, tenant_of=tenant_of)

    # snapshot AFTER the healthy window: the router block must describe
    # the chaos window it is printed next to, not fold in warmup and
    # healthy-window traffic
    fam_names = (
        "dalle_router_requests_total", "dalle_router_failovers_total",
        "dalle_router_hedges_total", "dalle_router_hedge_wins_total",
        "dalle_router_ejections_total", "dalle_router_unroutable_total",
    )
    before = {name: _fam(name) for name in fam_names}

    kill_at = 0.3 * duration_s

    def kill():
        servers[0].shutdown(drain=False)

    killed = run_fleet_window(
        port, arrivals, seeds + 1, on_offset=(kill_at, kill),
        tenant_of=tenant_of,
    )

    def _delta(name):
        prev = before[name]
        return {
            label: v - prev.get(label, 0)
            for label, v in _fam(name).items()
        }

    per_replica = _delta("dalle_router_requests_total")
    total_reqs = max(1, sum(per_replica.values()))
    line = {
        "bench": "serving_fleet",
        "engine": "continuous",
        "replicas": n_replicas,
        "max_batch": max_batch,
        "chunk_tokens": chunk_tokens,
        "rate_rps": round(rate, 3),
        "killed_replica": "r0",
        "kill_at_s": round(kill_at, 3),
        "healthy": healthy,
        "killed": killed,
        "router": {
            # killed-window DELTAS: what the chaos cost, not lifetime
            "failovers": _delta("dalle_router_failovers_total"),
            "hedges": _delta("dalle_router_hedges_total").get("total", 0),
            "hedge_wins": _delta("dalle_router_hedge_wins_total").get(
                "total", 0
            ),
            "ejections": _delta("dalle_router_ejections_total"),
            "unroutable": _delta("dalle_router_unroutable_total").get(
                "total", 0
            ),
            "retry_budget": round(router.budget.balance, 2),
            "per_replica_share": {
                name: round(v / total_reqs, 3)
                for name, v in per_replica.items()
            },
        },
        "fleet": _fleet_block(scraper, router),
        "p95_killed_vs_healthy": (
            round(killed["latency_p95_ms"] / healthy["latency_p95_ms"], 3)
            if killed["latency_p95_ms"] and healthy["latency_p95_ms"]
            else None
        ),
        "value": killed["completed"] / max(1, killed["offered"]),
        "metric": "fleet_completion_with_replica_killed",
        "unit": "fraction",
    }
    print(json.dumps(line), flush=True)

    front.shutdown()
    for s in servers[1:]:
        s.shutdown()


def main_drain_bench():
    """`--drain_bench`: rolling drain of one of two replicas mid-window,
    three flavors over identical Poisson schedules of 2-row requests:

      * `migrate`  — `drain?migrate=1`: the replica exports decode-state
        checkpoints at a chunk boundary; the router re-dispatches each
        in-flight request as a RESUME on the healthy replica.
      * `wait`     — the PR 12 graceful drain: stop admissions, wait out
        every outstanding row (zero re-decode, but the drain takes a
        full decode).
      * `failover` — the non-migrating baseline: a dispatch failure
        (FaultInjector) destroys the replica's decode state mid-window;
        recovery re-admits everything in flight FROM SCRATCH (the PR 11
        bounded retry) — the re-decode cost migration exists to cut.

    One JSON line: per-flavor client-visible errors, drain wall time,
    decoded/resumed token counters, and `re_decoded` (tokens decoded
    beyond what the completed requests strictly needed). The acceptance
    claim is migrate: zero errors, re_decoded strictly below kill's,
    drain wall far below wait's.
    """
    import numpy as np

    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
    from dalle_pytorch_tpu.serving.engine import ContinuousEngine
    from dalle_pytorch_tpu.serving.router import FleetRouter, RouterServer
    from dalle_pytorch_tpu.serving.server import ServingServer
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    # a bigger toy image than the other modes: the instrument measures
    # WORK IN FLIGHT at drain time, so decode must take long enough for
    # the drain to catch requests mid-image
    os.environ.setdefault("SERVE_FMAP", "8")
    chunk_tokens = int(os.environ.get("SERVE_CHUNK_TOKENS", "1"))
    max_batch = int(os.environ.get("SERVE_FLEET_SLOTS", "4"))
    duration_s = float(os.environ.get("SERVE_DRAIN_SECONDS", "8"))
    num_images = 2
    model, params, vae, vae_params, _text_ids = build_toy()
    image_seq = model.image_seq_len

    def build_fleet():
        servers = []
        for _ in range(2):
            eng = ContinuousEngine(
                model=model, variables=params, vae=vae,
                vae_params=vae_params, max_batch=max_batch,
                chunk_tokens=chunk_tokens, prefill_batch=max_batch,
                registry=MetricsRegistry(), resume_enabled=True,
            )
            eng.tokenizer = ByteTokenizer()
            servers.append(
                ServingServer(
                    eng, port=0, request_timeout_s=120,
                    max_queue_rows=max(64, 8 * max_batch),
                ).start()
            )
        router = FleetRouter(
            [f"r{i}=http://127.0.0.1:{s.port}"
             for i, s in enumerate(servers)],
            registry=MetricsRegistry(), probe_interval_s=0.25,
        )
        front = RouterServer(router, port=0).start()
        return servers, router, front

    servers, router, front = build_fleet()
    port = front.port

    warm_lat = []
    for i in range(6):
        out = fleet_request(
            port, {"prompt": "warm", "seed": 10_000 + i,
                   "num_images": num_images},
        )
        assert out["ok"], f"warmup request failed: {out}"
        warm_lat.append(out["latency_s"])
    image_s = max(min(warm_lat[-2:]), 1e-3)
    # 40% of optimistic fleet capacity: high enough that the drained
    # replica holds real in-flight work, low enough that the surviving
    # replica can absorb the post-drain window without shedding
    rate = 0.3 * 2 * max_batch / num_images / image_s
    rate = float(os.environ.get("SERVE_DRAIN_RPS", rate))
    rng = np.random.default_rng(
        int(os.environ.get("SERVE_ARRIVAL_SEED", "0"))
    )
    n = max(4, int(rate * duration_s))
    arrivals = np.sort(rng.uniform(0.0, duration_s, size=n))
    base_seeds = rng.integers(0, 2**30 - 1, size=n)
    drain_at = 0.3 * duration_s

    def counters(which):
        out = 0
        for s in servers:
            c = s.registry.get(f"dalle_serving_{which}_tokens_total")
            out += int(c.value) if c is not None else 0
        return out

    def run_window(mode, seeds):
        before_dec = counters("decoded")
        before_res = counters("resumed")
        drain_wall = {}

        def trigger():
            # wait (bounded) for r0 to actually HOLD work, so every
            # flavor measures a loaded-replica drain, not an empty one
            rep0 = router._find("r0")
            t_deadline = time.monotonic() + 5.0
            while rep0.outstanding_rows == 0 \
                    and time.monotonic() < t_deadline:
                time.sleep(0.002)
            drain_wall["caught_rows"] = rep0.outstanding_rows
            t0 = time.monotonic()
            if mode == "migrate":
                router.drain("r0", wait_s=60.0, migrate=True)
            elif mode == "wait":
                router.drain("r0", wait_s=120.0, propagate=True)
            else:
                # failover baseline: one injected chunk-dispatch failure
                # destroys r0's donated decode state; every in-flight
                # request suspends and re-admits FROM SCRATCH (the PR 11
                # bounded retry) — the exact re-decode a crash costs
                # today, without migration
                from dalle_pytorch_tpu.serving.faults import FaultInjector

                servers[0].engine.faults = FaultInjector().fail_nth(
                    "chunk", 1
                )
            drain_wall["s"] = time.monotonic() - t0

        results = [None] * len(arrivals)
        threads = []
        fired = threading.Event()
        trigger_thread = None
        t_start = time.monotonic()
        for i, (offset, seed) in enumerate(zip(arrivals, seeds)):
            delay = t_start + offset - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if not fired.is_set() and offset >= drain_at:
                fired.set()
                trigger_thread = threading.Thread(
                    target=trigger, daemon=True
                )
                trigger_thread.start()

            def client(i=i, seed=seed):
                results[i] = fleet_request(
                    port,
                    {"prompt": f"drain bench {seed}", "seed": int(seed),
                     "num_images": num_images, "timeout_s": 90},
                    timeout=95.0,
                )

            t = threading.Thread(target=client, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120.0)
        if trigger_thread is not None:
            # the wait-drain blocks until outstanding hits zero — join it
            # so drain_wall_s is the real number, not a race with the
            # last client's completion
            trigger_thread.join(timeout=150.0)
        done = [r for r in results if r is not None]
        completed = sum(1 for r in done if r["ok"])
        lat = sorted(r["latency_s"] for r in done if r["ok"])
        errors_by = {}
        for r in done:
            if not r["ok"]:
                key = str(r["status"] or r["error"])
                errors_by[key] = errors_by.get(key, 0) + 1
        decoded = counters("decoded") - before_dec
        resumed = counters("resumed") - before_res
        needed = completed * num_images * image_seq
        return {
            "offered": len(arrivals),
            "completed": completed,
            "errors": len(arrivals) - completed,
            "errors_by": errors_by,
            "drain_caught_rows": drain_wall.get("caught_rows", 0),
            "drain_wall_s": round(drain_wall.get("s", 0.0), 3),
            "decoded_tokens": decoded,
            "resumed_tokens": resumed,
            "needed_tokens": needed,
            # decode work beyond what the completed requests strictly
            # required — the lost-work number migration exists to cut
            "re_decoded_tokens": max(0, decoded - needed),
            "latency_p95_ms": (
                round(1000 * _percentile(lat, 0.95), 1) if lat else None
            ),
        }

    windows = {}
    windows["migrate"] = run_window("migrate", base_seeds)
    router.undrain("r0", propagate=True)
    # let the half-open trial re-admit r0 before the next window
    for i in range(4):
        fleet_request(port, {"prompt": "rejoin", "seed": 20_000 + i,
                             "num_images": num_images})
    windows["wait"] = run_window("wait", base_seeds + 1)
    router.undrain("r0", propagate=True)
    for i in range(4):
        fleet_request(port, {"prompt": "rejoin2", "seed": 30_000 + i,
                             "num_images": num_images})
    windows["failover"] = run_window("failover", base_seeds + 2)

    migs = router.registry.get("dalle_router_migrations_total")
    line = {
        "bench": "serving_drain",
        "engine": "continuous",
        "max_batch": max_batch,
        "chunk_tokens": chunk_tokens,
        "num_images": num_images,
        "rate_rps": round(rate, 3),
        "drain_at_s": round(drain_at, 3),
        "windows": windows,
        "router_migrations": (
            {label: int(c.value) for label, c in migs.items()}
            if migs is not None else {}
        ),
        "value": (
            1.0 if windows["migrate"]["errors"] == 0
            and windows["migrate"]["re_decoded_tokens"]
            < max(1, windows["failover"]["re_decoded_tokens"])
            else 0.0
        ),
        "metric": "migrating_drain_zero_error_and_less_redecode",
        "unit": "bool",
    }
    print(json.dumps(line), flush=True)

    front.shutdown()
    for s in servers:
        s.shutdown()


def _toy_checkpoint(path):
    """A loadable single-file DALLE checkpoint with randomly initialized
    toy weights — the restart bench measures BOOT cost (checkpoint load +
    compile), which does not care whether the model was trained."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.training.config import TrainConfig
    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer,
        dalle_from_config,
        dvae_hparams,
        save_dalle_checkpoint,
    )

    cfg = TrainConfig()
    cfg.model.dim = int(os.environ.get("SERVE_DIM", "64"))
    cfg.model.depth = int(os.environ.get("SERVE_DEPTH", "2"))
    cfg.model.heads = 2
    cfg.model.dim_head = cfg.model.dim // 2
    cfg.model.text_seq_len = int(os.environ.get("SERVE_TEXT_SEQ", "16"))
    cfg.model.shift_tokens = False
    cfg.model.rotary_emb = True
    fmap = int(os.environ.get("SERVE_FMAP", "4"))
    vae = DiscreteVAE(
        image_size=4 * fmap, num_layers=2, num_tokens=64,
        codebook_dim=32, hidden_dim=16,
    )
    vae_params = jax.jit(vae.init)(
        jax.random.PRNGKey(1), jnp.zeros((1, 4 * fmap, 4 * fmap, 3))
    )["params"]
    tokenizer = build_tokenizer(cfg)
    model = dalle_from_config(
        cfg, num_image_tokens=vae.num_tokens, image_fmap_size=fmap,
        vocab_size=max(tokenizer.vocab_size, 1),
    )
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0),
        jnp.zeros((1, cfg.model.text_seq_len), jnp.int32),
        jnp.zeros((1, fmap * fmap), jnp.int32),
    )
    save_dalle_checkpoint(
        str(path), cfg, variables["params"], vae_params, 0,
        "DiscreteVAE", vae_hparams=dvae_hparams(vae),
    )
    return path


class _ReplicaProc:
    """One serve.py subprocess with its stdout harvested into structured
    log records (the boot bench reads warmup_done; the supervised bench
    reads replica_start/replica_ready pids and timings)."""

    def __init__(self, argv, env=None):
        import subprocess
        import sys

        self.t0 = time.perf_counter()
        self.proc = subprocess.Popen(
            [sys.executable] + argv, text=True, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        self.lines = []
        self.events = []
        self._lock = threading.Lock()
        self.ready_at = None
        self.port = None
        self._ready = threading.Event()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._lock:
                self.lines.append(line)
            if "listening on http://" in line:
                self.ready_at = time.perf_counter()
                self.port = int(
                    line.split("http://")[1].split()[0].rsplit(":", 1)[1]
                )
                self._ready.set()
            elif line.startswith("{"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                with self._lock:
                    self.events.append(rec)
        self._ready.set()  # EOF: unblock waiters (boot failed)

    def wait_ready(self, timeout=600.0):
        ok = self._ready.wait(timeout) and self.port is not None
        with self._lock:
            tail = "".join(self.lines[-40:])
        assert ok, "replica never came up:\n" + tail
        return self.ready_at - self.t0

    def event(self, name, default=None):
        with self._lock:
            events = list(self.events)
        for rec in reversed(events):
            if rec.get("event") == name:
                return rec
        return default

    def stop(self, sig=None):
        import signal as _signal

        if self.proc.poll() is None:
            self.proc.send_signal(sig or _signal.SIGTERM)
            try:
                self.proc.wait(timeout=60)
            except Exception:
                self.proc.kill()
        self._reader.join(timeout=5)


def _serve_argv(ckpt, cache_dir, port, chunk_tokens):
    from pathlib import Path

    return [
        str(Path(__file__).parent / "serve.py"),
        "--dalle_path", str(ckpt), "--port", str(port),
        "--engine", "continuous", "--batch_shapes", "1,4",
        "--chunk_tokens", str(chunk_tokens),
        "--compile_cache", str(cache_dir),
        "--no_request_log",
    ]


def main_restart_bench():
    """`--restart_bench`: two JSON lines.

    1. serving_restart — boot-to-first-token of the SAME checkpoint,
       cold compile cache vs warm (the crash-fast recovery claim: a
       restarted replica's boot cost is cache load, not XLA).
    2. serving_supervised_restart — a 2-replica fleet behind a real
       router, replica 0 under `serve.py --supervise` with a warm
       cache; its serving child is SIGKILLed mid-window; the line
       reports completion (must be 1.0), the supervisor's restart
       count, the child's time-to-ready, and the router's
       ejected->half_open->healthy rejoin accounting.
    """
    import os as _os
    import signal as _signal
    import tempfile
    from pathlib import Path

    import numpy as np

    from dalle_pytorch_tpu.serving.router import FleetRouter, RouterServer
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    chunk_tokens = int(_os.environ.get("SERVE_CHUNK_TOKENS", "4"))
    work = Path(tempfile.mkdtemp(prefix="dalle_restart_bench_"))
    ckpt = _toy_checkpoint(work / "dalle.npz")
    cache_dir = work / "compile_cache"
    env = dict(_os.environ)
    env["DALLE_TPU_FORCE_PLATFORM"] = env.get(
        "DALLE_TPU_FORCE_PLATFORM", ""
    ) or env.get("JAX_PLATFORMS", "") or "cpu"

    def boot_once():
        rep = _ReplicaProc(
            _serve_argv(ckpt, cache_dir, 0, chunk_tokens), env=env
        )
        boot_s = rep.wait_ready()
        t0 = time.perf_counter()
        out = fleet_request(
            rep.port, {"prompt": "restart bench", "seed": 1234},
            timeout=300,
        )
        assert out["ok"], out
        first_s = time.perf_counter() - t0
        warmup = rep.event("warmup_done", {})
        rep.stop()
        return {
            "boot_s": round(boot_s, 2),
            "first_request_s": round(first_s, 3),
            "boot_to_first_token_s": round(boot_s + first_s, 2),
            "compiles": warmup.get("compiles"),
            "cache_hits": warmup.get("cache_hits"),
            "uncached_compiles": warmup.get("uncached_compiles"),
            "boot_cache_mode": warmup.get("boot_cache_mode"),
            "boot_seconds": warmup.get("boot_seconds"),
        }

    cold = boot_once()
    warm = boot_once()
    print(json.dumps({
        "bench": "serving_restart",
        "engine": "continuous",
        "chunk_tokens": chunk_tokens,
        "cold": cold,
        "warm": warm,
        "boot_speedup": round(
            cold["boot_to_first_token_s"]
            / max(warm["boot_to_first_token_s"], 1e-6), 2,
        ),
        "value": warm["boot_to_first_token_s"],
        "metric": "warm_boot_to_first_token_seconds",
        "unit": "s",
    }), flush=True)

    # ---- supervised kill -> restart -> rejoin window -------------------
    duration_s = float(_os.environ.get("SERVE_RESTART_SECONDS", "30"))
    import socket as _socket

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    r0_port = probe.getsockname()[1]
    probe.close()
    sup = _ReplicaProc(
        _serve_argv(ckpt, cache_dir, r0_port, chunk_tokens)
        + ["--supervise"],
        env=env,
    )
    r1 = _ReplicaProc(
        _serve_argv(ckpt, cache_dir, 0, chunk_tokens), env=env
    )
    sup.wait_ready()
    r1.wait_ready()
    router = FleetRouter(
        [
            f"r0=http://127.0.0.1:{r0_port}",
            f"r1=http://127.0.0.1:{r1.port}",
        ],
        registry=MetricsRegistry(),
        probe_interval_s=0.25,
    )
    front = RouterServer(router, port=0).start()
    try:
        warm_lat = []
        for i in range(6):
            out = fleet_request(
                front.port, {"prompt": "warm", "seed": 20_000 + i},
                timeout=300,
            )
            assert out["ok"], out
            warm_lat.append(out["latency_s"])
        image_s = max(min(warm_lat[-2:]), 1e-3)
        rate = 0.25 * 2 * 4 / image_s  # 25% of optimistic fleet capacity
        rate = float(_os.environ.get("SERVE_RESTART_RPS", rate))
        rng = np.random.default_rng(0)
        n = max(8, int(rate * duration_s))
        arrivals = np.sort(rng.uniform(0.0, duration_s, size=n))
        seeds = rng.integers(0, 2**31 - 1, size=n)

        start_rec = sup.event("replica_start")
        child_pid = int(start_rec["pid"])
        kill_at = 0.25 * duration_s

        def kill():
            _os.kill(child_pid, _signal.SIGKILL)

        window = run_fleet_window(
            front.port, arrivals, seeds, timeout_s=120.0,
            on_offset=(kill_at, kill),
        )
        # wait out the rejoin so the attribution below is complete
        deadline = time.monotonic() + 120
        rep0 = router.replicas[0]
        while rep0.restarts < 1 and time.monotonic() < deadline:
            fleet_request(
                front.port,
                {"prompt": "rejoin", "seed": int(time.monotonic() * 1e3)},
                timeout=300,
            )
            time.sleep(0.25)
        ready = sup.event("replica_ready", {})
        line = {
            "bench": "serving_supervised_restart",
            "engine": "continuous",
            "replicas": 2,
            "rate_rps": round(rate, 3),
            "duration_s": duration_s,
            "kill_at_s": round(kill_at, 2),
            "window": window,
            "supervisor": {
                "restarts": int(ready.get("restarts", 0)),
                "time_to_ready_s": ready.get("time_to_ready_s"),
            },
            "router": {
                "r0_restarts": rep0.restarts,
                "r0_rejoin_s": (
                    round(rep0.last_rejoin_s, 2)
                    if rep0.last_rejoin_s is not None else None
                ),
                "r0_down_reason": rep0.last_down_reason,
                "r0_state": rep0.state(),
            },
            "value": window["completed"] / max(1, window["offered"]),
            "metric": "supervised_restart_completion",
            "unit": "fraction",
        }
        print(json.dumps(line), flush=True)
    finally:
        front.shutdown()
        sup.stop()
        r1.stop()


def main_closed_loop():
    sweep = [
        int(c) for c in os.environ.get("SERVE_SWEEP", "1,4,8").split(",")
    ]
    requests_per_client = int(os.environ.get("SERVE_REQUESTS", "8"))
    delay_ms = float(os.environ.get("SERVE_DELAY_MS", "25"))

    engine, text_ids = build_engine()
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    results = [
        run_level(engine, text_ids, c, requests_per_client, delay_ms)
        for c in sweep
    ]
    top = results[-1]
    import jax

    record = {
        "metric": METRIC,
        "value": top["rps"],
        "unit": UNIT,
        "ok": all(r["errors"] == 0 for r in results),
        "device": jax.devices()[0].platform,
        "warmup_s": round(warmup_s, 2),
        "compiled_shapes": list(engine.stats.compiled_shapes),
        "max_delay_ms": delay_ms,
        "requests_per_client": requests_per_client,
        "sweep": results,
    }
    print(json.dumps(record))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--mode", choices=("closed-loop", "open-loop"),
        default=os.environ.get("SERVE_MODE", "closed-loop"),
    )
    p.add_argument(
        "--prompt_reuse", type=float,
        default=float(os.environ.get("SERVE_PROMPT_REUSE", "0")),
        help="open-loop: probability an arrival repeats a prompt from a "
        "Zipf-ish popularity pool instead of drawing a unique one "
        "(repeat prompts are the prefix cache's workload; 0 = legacy "
        "all-unique mix)",
    )
    p.add_argument(
        "--kv_layout", choices=("slot", "paged"),
        default=os.environ.get("SERVE_KV_LAYOUT", "slot"),
        help="open-loop: continuous engine cache layout (paged adds "
        "block_occupancy + prefix-cache stats and hit-vs-cold TTFT "
        "splits to its JSON line; SERVE_PAGE_SIZE / SERVE_KV_PAGES size "
        "the pool)",
    )
    p.add_argument(
        "--mesh", type=str, default=os.environ.get("SERVE_MESH") or None,
        help="open-loop: run the continuous side as a mesh-sharded "
        "engine (axis=size pairs over dp/fsdp/tp/sp, e.g. 'tp=2'); the "
        "JSON line gains a `mesh` block with axis sizes and per-device "
        "memory peaks (slot and paged layouts both shard)",
    )
    p.add_argument(
        "--kv_dtype", choices=("model", "int8"),
        default=os.environ.get("SERVE_KV_DTYPE", "model"),
        help="open-loop: continuous-engine KV-cache storage dtype; int8 "
        "stores pages/lanes quantized (per-(position, head) scales, "
        "in-kernel dequant) and adds a `quality` block — toy-CLIP score "
        "mean/delta vs the bf16 reference on the same (prompt, seed) "
        "rows — beside kv_bytes_per_slot",
    )
    p.add_argument(
        "--decode_sparsity", choices=("causal", "policy"),
        default=os.environ.get("SERVE_DECODE_SPARSITY", "causal"),
        help="open-loop: continuous-engine decode-attention sparsity; "
        "policy builds the toy with alternating full/axial layers and "
        "routes masked rows through the block-sparse flash kernel "
        "(serving/sparsity.py bitmaps, SERVE_SPARSE_BLOCK tile width) — "
        "the JSON line gains kv_tiles_read/kv_tiles_skipped/"
        "kv_tile_skip_fraction and the toy-CLIP `quality` block vs the "
        "dense-masked reference",
    )
    p.add_argument(
        "--priority_mix", type=float,
        default=(
            float(os.environ["SERVE_PRIORITY_MIX"])
            if os.environ.get("SERVE_PRIORITY_MIX") else None
        ),
        help="open-loop QoS mode: fraction of arrivals submitted as "
        "priority 'high' (the rest 'low'), replayed at an OVERLOAD rate "
        "(SERVE_PRIORITY_OVERLOAD x measured saturation) against one "
        "continuous batcher with preemption + deadline shedding; the "
        "JSON line reports per-class TTFT percentiles, preemption/"
        "resumption/shed counts, and high-vs-unloaded p95 ratio",
    )
    p.add_argument(
        "--replicas", type=int,
        default=int(os.environ.get("SERVE_REPLICAS", "0")),
        help="fleet mode: N in-process continuous replicas behind a real "
        "FleetRouter, open-loop HTTP load, one replica hard-killed "
        "mid-window; the JSON line carries the healthy vs killed-window "
        "latency and the router's failover/hedge accounting "
        "(SERVE_FLEET_SECONDS / SERVE_FLEET_RPS / SERVE_HEDGE_MS)",
    )
    p.add_argument(
        "--drain_bench", action="store_true",
        default=os.environ.get("SERVE_DRAIN_BENCH", "0") in ("1", "true"),
        help="zero-lost-work drain mode: two continuous replicas behind "
        "a real router, one drained mid-window three ways — "
        "drain?migrate=1 (decode-state checkpoints re-dispatched as "
        "resumes), graceful wait-drain, and an injected state-loss "
        "failure (the non-migrating failover baseline); one JSON line "
        "with per-flavor errors, drain wall "
        "time, and re-decoded token counts "
        "(SERVE_DRAIN_SECONDS / SERVE_DRAIN_RPS)",
    )
    p.add_argument(
        "--restart_bench", action="store_true",
        default=os.environ.get("SERVE_RESTART_BENCH", "0") in ("1", "true"),
        help="crash-fast recovery mode: (1) boot-to-first-token of the "
        "same checkpoint cold vs warm compile cache, (2) a supervised "
        "replica SIGKILLed mid-window behind a real router — restart, "
        "half-open rejoin, completion fraction; one JSON line each "
        "(SERVE_RESTART_SECONDS / SERVE_RESTART_RPS)",
    )
    p.add_argument(
        "--stream", action="store_true",
        default=os.environ.get("SERVE_STREAM", "0") in ("1", "true"),
        help="streaming-previews mode: one continuous engine with the "
        "preview fill-decode program warmed, open-loop arrivals each "
        "carrying a live event stream; the JSON line reports TTFP "
        "(time-to-first-preview) p50/p95 alongside TTFT and the "
        "headline ttfp_p95_chunk_periods acceptance ratio "
        "(SERVE_PREVIEW_EVERY / SERVE_STREAM_SECONDS)",
    )
    p.add_argument(
        "--trace_export", action="store_true",
        default=os.environ.get("SERVE_TRACE_EXPORT", "0") in ("1", "true"),
        help="open-loop: trace every measured request through an "
        "in-process fleet collector (obs/collector.py) and add a "
        "`critical_path` block — per-stage fleet p50/p95 plus dominant-"
        "stage attribution over the measured window only — to each "
        "engine's JSON line",
    )
    args = p.parse_args()
    if args.stream:
        main_stream_bench(kv_layout=args.kv_layout)
    elif args.drain_bench:
        main_drain_bench()
    elif args.restart_bench:
        main_restart_bench()
    elif args.replicas:
        hedge = os.environ.get("SERVE_HEDGE_MS")
        main_fleet(
            args.replicas,
            hedge_after_ms=float(hedge) if hedge else None,
        )
    elif args.mode == "open-loop" and args.priority_mix is not None:
        main_priority_mix(
            args.priority_mix, kv_layout=args.kv_layout,
            prompt_reuse=args.prompt_reuse,
        )
    elif args.mode == "open-loop":
        main_open_loop(
            prompt_reuse=args.prompt_reuse, kv_layout=args.kv_layout,
            mesh=args.mesh, trace_export=args.trace_export,
            kv_dtype=args.kv_dtype,
            decode_sparsity=args.decode_sparsity,
        )
    else:
        main_closed_loop()


if __name__ == "__main__":
    main()
