#!/usr/bin/env python
"""Train the DiscreteVAE (TPU-native train_vae).

Equivalent of `/root/reference/train_vae.py`: dVAE training with gumbel
temperature annealing (`:278`), exponential LR decay (`:158`),
reconstruction grids + codebook-usage histogram every 100 steps
(`:252-271`), per-epoch checkpoints. The whole optimizer step is one jitted
XLA program, sharded over the data axes of the device mesh.

Usage:
  python train_vae.py --image_folder <dir|rainbow[:N]> [--config cfg.yaml]
      [--set vae.num_tokens=1024] [--set learning_rate=1e-3] ...
"""

from __future__ import annotations

import argparse
import math
import time
from pathlib import Path

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", type=str, default=None, help="YAML config file")
    p.add_argument("--image_folder", type=str, default=None)
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="config override, e.g. --set vae.num_tokens=1024",
    )
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--learning_rate", type=float, default=None)
    p.add_argument("--output", type=str, default="vae.npz")
    p.add_argument("--lr_decay_rate", type=float, default=0.98)
    p.add_argument("--debug", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os as _os

    if _os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", _os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp

    from dalle_pytorch_tpu.parallel import (
        make_mesh, batch_sharding, state_shardings, is_root, put_host_batch,
        gather_to_host,
    )
    from dalle_pytorch_tpu.parallel import initialize_distributed

    # multi-host rendezvous (launch.py env vars / TPU pod auto); no-op
    # single-host. Must run before the first device query.
    initialize_distributed()
    from dalle_pytorch_tpu.training import (
        TrainState, make_optimizer, make_vae_train_step, make_multi_step,
        window_keys,
        stack_batches, window_iter, ExponentialDecay, set_learning_rate,
        get_learning_rate,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dalle_pytorch_tpu.training.config import load_config
    from dalle_pytorch_tpu.training.metrics import MetricsLogger, ThroughputMeter
    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer, build_dataset, vae_from_config, save_vae_checkpoint,
    )

    cfg = load_config(args.config, args.set)
    for k in ("epochs", "batch_size", "learning_rate"):
        v = getattr(args, k)
        if v is not None:
            setattr(cfg, k, v)
    if args.image_folder:
        cfg.image_text_folder = args.image_folder
    if args.debug:
        cfg.debug = True

    vae = vae_from_config(cfg.vae)
    tokenizer = build_tokenizer(cfg)
    dataset = build_dataset(cfg, tokenizer, image_size=cfg.vae.image_size)
    print(f"{len(dataset)} images for training")

    rng = jax.random.PRNGKey(cfg.seed)
    rng, init_rng, gumbel_rng = jax.random.split(rng, 3)
    sample = jnp.zeros((1, cfg.vae.image_size, cfg.vae.image_size, cfg.vae.channels))
    params = vae.init({"params": init_rng, "gumbel": gumbel_rng}, sample)["params"]
    state = TrainState.create(
        apply_fn=vae.apply, params=params, tx=make_optimizer(cfg.learning_rate)
    )

    mesh = make_mesh(
        dp=cfg.mesh.dp, fsdp=cfg.mesh.fsdp, tp=cfg.mesh.tp, sp=cfg.mesh.sp
    )
    state_sh = state_shardings(state, mesh)
    img_sh = batch_sharding(mesh, extra_dims=3)
    state = jax.device_put(state, state_sh)
    raw_step = make_vae_train_step(vae, grad_accum=cfg.ga_steps)
    step_fn = jax.jit(
        raw_step,
        in_shardings=(state_sh, img_sh, None, None),
        out_shardings=(state_sh, None),
        donate_argnums=0,
    )
    # steps_per_dispatch>1: scan T steps into one dispatch (see
    # train_dalle.py). The gumbel temp rides as a per-dispatch constant,
    # updated per crossed 100-step boundary AFTER the window — so when
    # steps_per_dispatch does not divide 100, up to spd-1 steps of the
    # crossing window still run at the previous temperature/LR relative
    # to a single-step run (window-granularity anneal).
    steps_per_dispatch = max(1, int(cfg.steps_per_dispatch))
    multi_fn = None
    if steps_per_dispatch > 1:
        win_img_sh = NamedSharding(mesh, P(None, *img_sh.spec))
        multi_fn = jax.jit(
            make_multi_step(raw_step, steps_per_dispatch),
            in_shardings=(state_sh, win_img_sh, None, None),
            out_shardings=(state_sh, None),
            donate_argnums=0,
        )

    logger = MetricsLogger(
        project=cfg.project, config={"cli": "train_vae"},
        enabled=is_root(), debug=cfg.debug, out_dir=str(Path(cfg.output_dir) / "vae_logs"),
    )
    meter = ThroughputMeter()
    sched = ExponentialDecay(gamma=args.lr_decay_rate) if cfg.lr_decay else None

    temp = cfg.vae.temperature
    global_step = 0
    last_params_h = None
    shard = (jax.process_index(), jax.process_count())
    from dalle_pytorch_tpu.data.prefetch import Prefetcher

    for epoch in range(cfg.epochs):
        # background batch assembly + device transfer ahead of the step
        # (same input/compute overlap as train_dalle.py)
        def assemble(b):
            # (device_batch, host-local head) — recon-grid logging must not
            # fetch the global array (non-addressable on multi-host)
            return put_host_batch(b["images"], img_sh), np.asarray(b["images"][:4])

        def assemble_window(win):
            if len(win) < steps_per_dispatch:  # epoch tail: per-step replay
                return [assemble(b) for b in win], None
            stacked = stack_batches([b["images"] for b in win])
            return (
                put_host_batch(stacked, win_img_sh),
                np.asarray(win[0]["images"][:4]),
            )

        raw_batches = dataset.batches(
            cfg.batch_size, shuffle_seed=epoch, shard=shard
        )
        if steps_per_dispatch > 1:
            batch_iter = Prefetcher(
                window_iter(raw_batches, steps_per_dispatch),
                transform=assemble_window, depth=cfg.prefetch_depth,
            )
        else:
            batch_iter = Prefetcher(
                raw_batches, transform=assemble, depth=cfg.prefetch_depth
            )
        try:
            for images, images_head in batch_iter:
                prev_step = global_step
                # fold_in(step) keys (make_multi_step's prescription, as in
                # train_dalle.py): the stream is a pure function of the
                # global step, so runs are reproducible across
                # steps_per_dispatch settings and epoch tails
                if multi_fn is not None and not isinstance(images, list):
                    keys = window_keys(rng, global_step, steps_per_dispatch)
                    state, metrics = multi_fn(state, images, keys, jnp.float32(temp))
                    r = keys[-1]  # for the recon-grid gumbel sample below
                    global_step += steps_per_dispatch
                else:
                    singles = (
                        images if isinstance(images, list)
                        else [(images, images_head)]
                    )
                    for img_b, head_b in singles:
                        images_head = head_b
                        r = jax.random.fold_in(rng, global_step)
                        state, metrics = step_fn(state, img_b, r, jnp.float32(temp))
                        global_step += 1

                def crossed(interval):
                    return bool(interval) and (
                        global_step // interval > prev_step // interval
                    )

                log = {}
                if crossed(100):
                    # recon grids: soft (gumbel) + hard (argmax->decode),
                    # computed from the host-local head rows
                    k = images_head.shape[0]
                    head = jnp.asarray(images_head)
                    soft = vae.apply(
                        {"params": state.params}, head, temp=temp,
                        rngs={"gumbel": r},
                    )
                    codes = vae.apply(
                        {"params": state.params}, head,
                        method=type(vae).get_codebook_indices,
                    )
                    hard = vae.apply({"params": state.params}, codes, method=type(vae).decode)
                    # codebook usage histogram (`train_vae.py:256-260`)
                    usage = np.bincount(
                        np.asarray(codes).ravel(), minlength=cfg.vae.num_tokens
                    )
                    grid = np.concatenate(
                        [images_head, np.asarray(soft) * 0.5 + 0.5,
                         np.asarray(hard) * 0.5 + 0.5], axis=0
                    )
                    logger.log_images(grid, "orig | soft | hard", "recons", global_step)
                    # temperature anneal (`train_vae.py:278`) + LR decay:
                    # one application PER crossed 100-step boundary (a
                    # steps_per_dispatch>100 window can span several), each
                    # at its boundary's step value, so the schedule matches
                    # a single-step run regardless of window size
                    for boundary in range(
                        prev_step // 100 + 1, global_step // 100 + 1
                    ):
                        temp = max(
                            temp * math.exp(-cfg.vae.anneal_rate * boundary * 100),
                            cfg.vae.temp_min,
                        )
                        if sched is not None:
                            state = set_learning_rate(
                                state, sched.step(0.0, get_learning_rate(state))
                            )
                    log.update(
                        temperature=temp,
                        lr=get_learning_rate(state),
                        codebook_usage_frac=float((usage > 0).mean()),
                    )

                rate = meter.update(global_step, cfg.batch_size)
                if rate is not None:
                    log["sample_per_sec"] = rate
                if crossed(10):
                    log["loss"] = float(metrics["loss"])
                    print(epoch, global_step, f"loss - {log['loss']:.5f}")
                if log:
                    logger.log(log, step=global_step)

        finally:
            batch_iter.close()

        last_params_h = gather_to_host(state.params)  # collective; all hosts
        if is_root():
            save_vae_checkpoint(args.output, vae, last_params_h, epoch)
            print(f"epoch {epoch} done; checkpoint -> {args.output}")
            # per-epoch model artifact (`train_vae.py:305-310`)
            logger.log_model_artifact(args.output, "trained-vae")

    if last_params_h is None:  # epochs == 0: the loop never gathered
        last_params_h = gather_to_host(state.params)
    if is_root():
        save_vae_checkpoint(args.output, vae, last_params_h, cfg.epochs)
    logger.finish()


if __name__ == "__main__":
    main()
