#!/usr/bin/env python
"""Serve a trained DALL-E checkpoint over HTTP with dynamic micro-batching.

The production face of `generate.py`: the same `GenerationEngine` (KV-cached
scan decode, fused dVAE pixel decode, optional CLIP rerank), fed by a
bounded request queue that coalesces concurrent callers into fixed-shape
compiled batches. See README "Serving" for the API and metrics reference.

    python serve.py --dalle_path checkpoints/dalle.npz --port 8000
    curl -s localhost:8000/generate -d '{"prompt": "small red circle"}'
    curl -s localhost:8000/metrics
"""

from __future__ import annotations

import argparse
import signal
import sys


def parse_tenant_weights(text):
    """'a=4,b=1' -> {"a": 4.0, "b": 1.0}; raises ValueError on junk."""
    out = {}
    for pair in (text or "").split(","):
        if not pair:
            continue
        tenant, sep, weight = pair.partition("=")
        if not sep or not tenant:
            raise ValueError(f"expected tenant=weight, got {pair!r}")
        w = float(weight)
        if w <= 0:
            raise ValueError(f"tenant {tenant!r} weight must be > 0")
        out[tenant] = w
    return out


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dalle_path", type=str, default=None,
                   help="DALL-E checkpoint to serve (required unless "
                   "--router)")
    p.add_argument("--router", action="store_true",
                   help="run the replica fleet ROUTER instead of an "
                   "engine replica: front the --replicas URLs with "
                   "health-aware routing, failover retries under a "
                   "success-fraction retry budget, optional hedging, "
                   "and graceful drain (POST /admin/drain?replica=). "
                   "No checkpoint loads in this mode")
    from dalle_pytorch_tpu.serving.router import add_router_args

    add_router_args(p, require_replicas=False)
    p.add_argument("--clip_path", type=str, default=None,
                   help="optional CLIP checkpoint enabling rerank=true requests")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000, help="0 picks a free port")
    p.add_argument(
        "--batch_shapes", type=str, default="1,4,8",
        help="comma-separated compiled batch sizes; requests are padded up "
        "to the nearest shape (more shapes = less padding waste, more "
        "compiles at warmup)",
    )
    p.add_argument("--max_delay_ms", type=float, default=25.0,
                   help="micro-batch flush deadline from the oldest request")
    p.add_argument(
        "--engine", choices=("micro", "continuous"), default="micro",
        help="micro: padded micro-batches, one full decode scan per flush; "
        "continuous: token-boundary admission over cache slots (lower "
        "time-to-first-token under load; slot count = max of "
        "--batch_shapes; cond_scale must be 1)",
    )
    p.add_argument("--chunk_tokens", type=int, default=4,
                   help="continuous engine: tokens decoded per chunk "
                   "dispatch (smaller = faster admission/retirement, more "
                   "host round trips)")
    p.add_argument("--prefill_batch", type=int, default=4,
                   help="continuous engine: prompts admitted per prefill "
                   "dispatch (R pending requests cost ceil(R/prefill_batch) "
                   "dispatches at a chunk boundary; clamped to the slot "
                   "count)")
    p.add_argument(
        "--kv_layout", choices=("slot", "paged"), default="slot",
        help="continuous engine cache layout. slot: one full-length KV "
        "lane per slot (HBM = max_batch worst case); paged: block-paged "
        "pool + per-row page tables with content-hash prefix caching "
        "(HBM follows tokens actually held; repeat prompts admit with "
        "zero prefill dispatches)",
    )
    p.add_argument("--page_size", type=int, default=32,
                   help="paged layout: tokens per KV page (TPU wants a "
                   "multiple of 8 for the paged Pallas kernel)")
    p.add_argument("--kv_pages", type=int, default=None,
                   help="paged layout: physical pages in the pool "
                   "(default sizes the slotted worst case + one row of "
                   "prefix-cache headroom; size it DOWN to cap HBM — "
                   "admission then backpressures on free pages)")
    p.add_argument("--prefix_entries", type=int, default=64,
                   help="paged layout: prompts kept in the prefix cache "
                   "(0 disables prefix caching; LRU eviction)")
    p.add_argument("--mesh", type=str, default=None, metavar="AXES",
                   help="serve one engine SHARDED over a device mesh "
                   "(continuous engine, slot OR paged layout): axis=size "
                   "pairs over dp/fsdp/tp/sp, e.g. 'dp=1,tp=4'; one size "
                   "may be -1 to absorb the remaining devices. Params "
                   "shard per parallel/partition.py, the KV cache (slot "
                   "lanes or the paged page pool) over attention heads "
                   "(parallel/serving_partition.py); page tables stay "
                   "host-side. CPU smoke test: XLA_FLAGS="
                   "--xla_force_host_platform_device_count=8")
    p.add_argument("--kv_dtype", choices=("model", "int8"), default="model",
                   help="KV-cache storage dtype (continuous engine). "
                   "model: the model compute dtype (bit-identical "
                   "default); int8: pages/lanes stored quantized with "
                   "per-(position, head) fp32 scales, dequantized inside "
                   "the decode kernels — roughly 2x decode rows per HBM "
                   "byte (exactly 2D/(D+4) at head dim D) at a small "
                   "quantization error (bench_serving.py reports the "
                   "CLIP-score delta beside the speedup)")
    p.add_argument("--decode_sparsity", choices=("causal", "policy"),
                   default="causal",
                   help="decode-attention sparsity (continuous engine). "
                   "causal: dense-causal flash decode, the bit-identical "
                   "default; policy: pattern-masked layers route through "
                   "the block-sparse flash kernel with per-slot KV-tile "
                   "bitmaps derived host-side from the model's static "
                   "attention layouts (serving/sparsity.py) and shipped "
                   "as traced data — dead tiles skip compute AND DMA, "
                   "zero extra compiled programs after warmup "
                   "(bench_serving.py reports kv_tiles_skipped and the "
                   "CLIP-score delta beside the speedup)")
    p.add_argument("--max_queue", type=int, default=64,
                   help="queue bound in rows; beyond it requests get 503")
    p.add_argument("--request_timeout_s", type=float, default=120.0)
    p.add_argument("--no_preempt", action="store_true",
                   help="disable decode-time priority preemption "
                   "(continuous engine): a high-priority request blocked "
                   "on slots then waits for natural completions instead "
                   "of reclaiming a low-priority slot at a chunk boundary")
    p.add_argument("--no_shed", action="store_true",
                   help="disable deadline-aware admission shedding "
                   "(continuous engine): requests whose estimated "
                   "completion exceeds their own timeout queue anyway "
                   "instead of getting an immediate 503 + Retry-After")
    p.add_argument("--tenant_quota_rows", type=int, default=None,
                   help="per-tenant cap on queued request rows; a tenant "
                   "past it gets 429 + Retry-After (default: no quota)")
    p.add_argument("--tenant_weights", type=str, default=None,
                   metavar="T=W,...",
                   help="proportional per-tenant admission shares within "
                   "each priority class, e.g. 'a=4,b=1' (a backlogged "
                   "weight-4 tenant gets ~4x the rows of a weight-1 "
                   "one; unlisted tenants weigh 1; weights are shares, "
                   "--tenant_quota_rows stays the hard cap)")
    p.add_argument("--replica_quarantine_after", type=int, default=2,
                   help="replica-side poison threshold: a request that "
                   "died in flight for this many CONSECUTIVE failed "
                   "engine dispatches gets a terminal 422 with the "
                   "incident ids instead of a failover-inviting 500 "
                   "(default 2 pairs with the batcher's one bounded "
                   "retry; 0 disables — distinct from the router-level "
                   "--quarantine_after, which tracks replica CRASHES)")
    p.add_argument("--reserve_slots", type=int, default=0,
                   help="cache slots reserved for priority 'high' "
                   "requests (continuous engine): high arrivals admit at "
                   "the next chunk boundary without waiting for a "
                   "preemption cycle, at the cost of idle slots when "
                   "there is no high traffic (default 0: work-conserving, "
                   "preemption alone reclaims capacity)")
    p.add_argument("--cond_scale", type=float, default=1.0)
    p.add_argument("--no_warmup", action="store_true",
                   help="skip compiling all batch shapes at startup (first "
                   "request per shape then pays compile latency)")
    p.add_argument("--compile_cache", type=str, default=None, metavar="DIR",
                   help="persistent compile cache: jax's XLA executable "
                   "store plus fingerprinted AOT artifacts for every "
                   "warmed program live under DIR, so a restarted "
                   "replica (same checkpoint/config/jax/mesh) warms up "
                   "in seconds instead of recompiling — a mismatched or "
                   "corrupt cache degrades to a normal cold boot, never "
                   "a failed one (counted in "
                   "dalle_boot_cache_{hits,misses,rejects}_total)")
    p.add_argument("--no_resume", action="store_true",
                   help="drop the mid-decode resume program from the "
                   "continuous engine's warmup ladder: migrated/preempted "
                   "rows then restart decode at position 0 (bit-identical "
                   "output, more re-decoded work) instead of resuming at "
                   "their checkpointed position via one teacher-forced "
                   "re-prefill dispatch")
    p.add_argument("--checkpoint_spool", type=str, default=None,
                   metavar="DIR",
                   help="arm the crash progress beacon: every "
                   "--spool_every chunks the continuous batcher journals "
                   "in-flight decode-state checkpoints to DIR (one "
                   "atomic bounded file); after a crash the supervisor "
                   "hands the journal to the fleet router so interrupted "
                   "requests resume instead of re-decoding from scratch")
    p.add_argument("--spool_every", type=int, default=8,
                   help="chunk boundaries between beacon writes (a hard "
                   "kill loses at most this many chunks of journaled "
                   "progress)")
    p.add_argument("--preview_every", type=int, default=4,
                   help="streaming /generate: decode chunks between "
                   "progressive preview events (partial token grid filled "
                   "with the mean codebook token, run through the warmed "
                   "fill+decode program, shipped as base64 PNG). 0 "
                   "disables previews — and drops the preview program "
                   "from the warmup ladder — while per-chunk progress "
                   "events still flow")
    p.add_argument("--spool_notify", type=str, default=None, metavar="URL",
                   help="with --supervise: fleet router base URL the "
                   "supervisor POSTs the spool to (/admin/spool) once "
                   "the restarted replica is ready")
    p.add_argument("--supervise", action="store_true",
                   help="run this replica under the crash-fast "
                   "supervisor: the server becomes a subprocess that is "
                   "restarted on abnormal exit with capped exponential "
                   "backoff and crash-loop hold-down, readiness gated "
                   "on its real /healthz (pair with --compile_cache so "
                   "restarts rejoin in seconds). Needs an explicit "
                   "--port")
    p.add_argument("--verbose", action="store_true", help="HTTP access logs")
    p.add_argument("--trace-dump", "--trace_dump", dest="trace_dump",
                   type=str, default=None, metavar="PATH",
                   help="write the request-trace ring buffer as Perfetto "
                   "trace_event JSON to PATH on drain/shutdown (the live "
                   "view is GET /debug/traces)")
    p.add_argument("--trace_ring", type=int, default=256,
                   help="how many recent request traces to keep in memory")
    p.add_argument("--trace_export", type=str, default=None, metavar="URL",
                   help="ship finished request traces to a fleet trace "
                   "collector (python -m dalle_pytorch_tpu.obs.collector) "
                   "at URL as batched JSONL — bounded buffer + backoff; "
                   "serving is unaffected when the collector is down")
    p.add_argument("--trace_site", type=str, default=None, metavar="NAME",
                   help="stable process identity for fleet traces and "
                   "request-log lines (one track per site in the "
                   "collector's merged view; default: hostname)")
    p.add_argument("--no_tracing", action="store_true",
                   help="disable the request span tracer entirely "
                   "(/debug/traces serves an empty trace; stage metrics "
                   "on /metrics still work)")
    p.add_argument("--profile_dir", type=str, default="profiles",
                   help="where POST /debug/profile?seconds=N writes its "
                   "TensorBoard trace directories")
    p.add_argument("--no_request_log", action="store_true",
                   help="suppress the structured JSON log line per "
                   "completed request")
    p.add_argument("--request_log_path", type=str, default=None,
                   metavar="FILE",
                   help="write structured JSONL to FILE instead of "
                   "stdout (append mode; lifecycle events included)")
    p.add_argument("--request_log_max_mb", type=float, default=None,
                   metavar="MB",
                   help="rotate --request_log_path once it exceeds MB "
                   "megabytes: the full file is renamed to FILE.1 "
                   "(keep one) and a fresh file is started, so disk "
                   "use stays bounded at ~2x the cap")
    p.add_argument("--no_vitals", action="store_true",
                   help="disable the engine-vitals sampler (and with it "
                   "the stall watchdog and SLO burn tracking); "
                   "/debug/vitals then serves an empty ring")
    p.add_argument("--vitals_interval_s", type=float, default=1.0,
                   help="seconds between vitals snapshots / watchdog "
                   "checks")
    p.add_argument("--no_program_costs", action="store_true",
                   help="skip per-program XLA cost capture at warmup "
                   "(saves one extra AOT compile per program; "
                   "/debug/programs and the MFU gauges then stay empty)")
    p.add_argument("--slo_ttft_ms", type=float, default=None,
                   help="time-to-first-token SLO target in ms "
                   "(continuous engine); burn rate over the rolling "
                   "window drives the /healthz degraded tier and "
                   "dalle_slo_burn_rate{slo=\"ttft\"}")
    p.add_argument("--slo_request_ms", type=float, default=None,
                   help="end-to-end request latency SLO target in ms")
    p.add_argument("--slo_objective", type=float, default=0.99,
                   help="fraction of requests that must meet each SLO "
                   "target (error budget = 1 - objective)")
    p.add_argument("--slo_window_s", type=float, default=300.0,
                   help="rolling window for SLO burn-rate computation")
    args = p.parse_args(argv)
    if args.supervise:
        if args.router:
            p.error("--supervise supervises an engine replica; run the "
                    "router under its own process manager")
        if args.port == 0:
            p.error("--supervise needs an explicit --port (the "
                    "supervisor probes http://host:port/healthz for "
                    "readiness; port 0 would pick a fresh one per "
                    "restart)")
    if args.spool_notify is not None and not args.supervise:
        p.error("--spool_notify is the supervisor's hand-off hook; it "
                "needs --supervise")
    if args.spool_notify is not None and args.checkpoint_spool is None:
        p.error("--spool_notify needs --checkpoint_spool (nothing to "
                "hand over otherwise)")
    if args.checkpoint_spool is not None and (
        args.router or args.engine != "continuous"
    ):
        p.error("--checkpoint_spool needs --engine continuous (the "
                "router and the micro engine hold no resumable decode "
                "state)")
    if args.spool_every < 1:
        p.error("--spool_every must be >= 1")
    if args.preview_every < 0:
        p.error("--preview_every must be >= 0 (0 disables previews)")
    if args.request_log_max_mb is not None:
        if args.request_log_path is None:
            p.error("--request_log_max_mb rotates a log file; it needs "
                    "--request_log_path")
        if args.request_log_max_mb <= 0:
            p.error("--request_log_max_mb must be > 0")
    if args.router:
        if not args.replicas:
            p.error("--router needs --replicas URL[,URL...]")
        if args.dalle_path is not None:
            p.error("--router does not load a checkpoint; drop "
                    "--dalle_path (replicas load their own)")
        if args.no_tracing and args.trace_export is not None:
            p.error("--trace_export needs the span tracer; drop "
                    "--no_tracing")
        return args
    if args.dalle_path is None:
        p.error("--dalle_path is required (unless running --router)")
    if args.replicas is not None:
        p.error("--replicas only applies with --router")
    try:
        args.tenant_weights = parse_tenant_weights(args.tenant_weights) or None
    except ValueError as exc:
        p.error(f"bad --tenant_weights: {exc}")
    if args.mesh is not None:
        # fail at parse time, not after the checkpoint loads: both the
        # engine mode and the mesh string itself (slot AND paged layouts
        # both shard — the paged pool head-splits, tables stay host-side)
        if args.engine != "continuous":
            p.error("--mesh needs --engine continuous")
        from dalle_pytorch_tpu.serving.sharded import parse_mesh_shape

        try:
            parse_mesh_shape(args.mesh)
        except (AssertionError, ValueError) as exc:
            p.error(f"bad --mesh {args.mesh!r}: {exc}")
    if args.no_vitals and (
        args.slo_ttft_ms is not None or args.slo_request_ms is not None
    ):
        # the sampler thread drives SLO burn updates; without it the
        # gauge would sit at 0 forever — fail loudly, not silently
        p.error("--slo_ttft_ms/--slo_request_ms need the vitals sampler; "
                "drop --no_vitals")
    if args.tenant_quota_rows is not None and args.tenant_quota_rows < 1:
        p.error("--tenant_quota_rows must be >= 1 (omit it for no quota)")
    if args.replica_quarantine_after < 0:
        p.error("--replica_quarantine_after must be >= 0 (0 disables)")
    max_shape = max(
        (int(b) for b in args.batch_shapes.split(",") if b), default=1
    )
    if not 0 <= args.reserve_slots < max_shape:
        p.error(f"--reserve_slots must be in [0, {max_shape - 1}] so at "
                "least one slot stays usable by every class")
    if args.trace_export is not None and args.no_tracing:
        # the exporter ships finished traces; a disabled tracer never
        # finishes any — fail loudly, not with a silently idle exporter
        p.error("--trace_export needs the span tracer; drop --no_tracing")
    return args


import contextlib


@contextlib.contextmanager
def _null_phase(name):
    """Boot-phase timer stand-in when no compile cache is configured."""
    yield


def run_router(args):
    """`serve.py --router`: the fleet admission router in front of N
    replicas — no jax, no checkpoint, stdlib HTTP only. One shared run
    loop with `python -m dalle_pytorch_tpu.serving.router`."""
    from dalle_pytorch_tpu.obs.logging import StructuredLog
    from dalle_pytorch_tpu.serving.router import run_router_server

    log = StructuredLog(component="dalle.router", site=args.trace_site,
                        path=args.request_log_path,
                        max_mb=args.request_log_max_mb)
    return run_router_server(args, log=log)


def main(argv=None):
    args = parse_args(argv)
    if args.router:
        return run_router(args)
    if args.supervise:
        # BEFORE the jax import: the supervisor process only spawns and
        # probes — the child pays the runtime, and pays it again per
        # restart (which is exactly what --compile_cache amortizes)
        from dalle_pytorch_tpu.serving.supervisor import supervise_serve

        return supervise_serve(args, argv)
    import jax
    import os as _os

    if _os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", _os.environ["DALLE_TPU_FORCE_PLATFORM"])

    from dalle_pytorch_tpu.obs import (
        EngineVitals, ProfilerCapture, ProgramCostTable, SLOTarget,
        SLOTracker, StallWatchdog, StructuredLog, TraceExporter, Tracer,
    )
    from dalle_pytorch_tpu.serving import ServingServer, engine_from_checkpoint
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry
    from dalle_pytorch_tpu.utils import compile_guard
    from dalle_pytorch_tpu.utils.compile_cache import (
        CompileCache, boot_fingerprint,
    )

    # structured JSONL on stdout replaces the old ad-hoc status prints;
    # the one surviving print is the "[serve] listening" readiness line,
    # which orchestrators pattern-match. --no_request_log drops only the
    # per-request lines; lifecycle events (warmup, trace_dump, shutdown)
    # always flow. --trace_site stamps every line's process identity so
    # fleet logs merge and join against collector traces by trace_id.
    log = StructuredLog(site=args.trace_site, path=args.request_log_path,
                        max_mb=args.request_log_max_mb)

    registry = MetricsRegistry()
    cache = None
    if args.compile_cache:
        # install BEFORE anything compiles: the persistent XLA store must
        # see the warmup ladder's compiles (and serve them back next boot)
        cache = CompileCache(
            args.compile_cache, registry=registry, log=log
        ).install()

    batch_shapes = tuple(int(b) for b in args.batch_shapes.split(",") if b)
    phases = cache.boot_phase if cache is not None else _null_phase
    with phases("checkpoint"):
        engine = engine_from_checkpoint(
            args.dalle_path,
            clip_path=args.clip_path,
            batch_shapes=batch_shapes,
            cond_scale=args.cond_scale,
            registry=registry,
            mode=args.engine,
            chunk_tokens=args.chunk_tokens,
            prefill_batch=args.prefill_batch,
            kv_layout=args.kv_layout,
            page_size=args.page_size,
            kv_pages=args.kv_pages,
            prefix_entries=args.prefix_entries,
            mesh=args.mesh,
            kv_dtype=args.kv_dtype,
            decode_sparsity=args.decode_sparsity,
            resume_enabled=not args.no_resume,
            # --preview_every 0 drops the preview fill+decode program
            # from the warmup ladder entirely (micro engines never
            # stream, so the knob is continuous-only either way)
            preview_enabled=args.preview_every > 0,
        )
    if cache is not None:
        # identity of this compiled-ladder universe: any drift (jax
        # upgrade, backend, mesh, model config, new program) turns the
        # on-disk artifacts into counted misses and the boot goes cold
        with phases("plan"):
            cache.bind(
                boot_fingerprint(
                    backend=jax.default_backend(),
                    mesh_shape=args.mesh,
                    model_config=engine.cfg,
                    programs=engine.program_ladder(),
                ),
                engine.program_ladder(),
            )
            cache.plan_boot()
        engine.compile_cache = cache
    if not args.no_program_costs:
        # attach BEFORE warmup: capture happens while the ladder compiles
        # (one extra AOT compile per program — the price of
        # /debug/programs rows and live MFU gauges)
        engine.cost_table = ProgramCostTable(registry=engine.registry)
    if not args.no_warmup:
        log.event("warmup_start", batch_shapes=list(engine.batch_shapes))
        with compile_guard.track_compiles() as tally:
            with phases("warmup"):
                engine.warmup()
        # compiles vs cache_hits is the warm-boot receipt: a second boot
        # against a matching cache logs uncached_compiles=0 (pinned by
        # the slow-tier recovery test)
        log.event(
            "warmup_done",
            compiled_shapes=list(engine.stats.compiled_shapes),
            compiles=tally.count,
            cache_hits=tally.cache_hits,
            uncached_compiles=tally.uncached,
            boot_cache_mode=cache.plan["mode"] if cache is not None else None,
            boot_seconds=dict(cache.boot_seconds) if cache is not None else None,
        )

    crash_spec = _os.environ.get("DALLE_SERVE_CRASH")
    if crash_spec:
        # chaos-only seam (recovery drills, the supervised-restart
        # bench): hard-abort this replica at the Nth dispatch of a named
        # program, e.g. DALLE_SERVE_CRASH=chunk:3
        from dalle_pytorch_tpu.serving import FaultInjector

        prog, _, nth = crash_spec.partition(":")
        engine.faults = FaultInjector().crash_nth(prog, int(nth or 1))
        log.event("chaos_crash_armed", program=prog, nth=int(nth or 1))

    slo_targets = []
    if args.slo_ttft_ms is not None:
        slo_targets.append(SLOTarget(
            "ttft", args.slo_ttft_ms / 1000.0,
            histogram="dalle_serving_ttft_seconds",
            objective=args.slo_objective,
        ))
    if args.slo_request_ms is not None:
        slo_targets.append(SLOTarget(
            "request", args.slo_request_ms / 1000.0,
            histogram="dalle_serving_request_latency_seconds",
            objective=args.slo_objective,
        ))
    vitals = EngineVitals(
        enabled=not args.no_vitals,
        interval_s=args.vitals_interval_s,
        registry=engine.registry,
        log=log,
        watchdog=StallWatchdog(
            registry=engine.registry,
            # a queued head older than the request timeout should already
            # have been failed by the worker; half of it is "stale"
            queue_age_budget_s=args.request_timeout_s / 2.0,
        ),
        slo=(
            SLOTracker(
                slo_targets, registry=engine.registry,
                window_s=args.slo_window_s,
            )
            if slo_targets else None
        ),
    )

    exporter = None
    if args.trace_export is not None:
        # the exporter registers its drop/sent/retry counters on the
        # engine registry so /metrics carries fleet-export health
        exporter = TraceExporter(
            args.trace_export, site=args.trace_site,
            registry=engine.registry,
        )
        log.event("trace_export", url=exporter.url, site=exporter.site)

    server = ServingServer(
        engine,
        host=args.host,
        port=args.port,
        max_delay_ms=args.max_delay_ms,
        max_queue_rows=args.max_queue,
        request_timeout_s=args.request_timeout_s,
        verbose=args.verbose,
        tracer=Tracer(
            enabled=not args.no_tracing, max_traces=args.trace_ring
        ),
        exporter=exporter,
        log=log,
        log_requests=not args.no_request_log,
        profiler=ProfilerCapture(out_dir=args.profile_dir),
        trace_dump_path=args.trace_dump,
        vitals=vitals,
        tenant_quota_rows=args.tenant_quota_rows,
        tenant_weights=args.tenant_weights,
        preempt=not args.no_preempt,
        deadline_shed=not args.no_shed,
        reserve_slots=args.reserve_slots,
        quarantine_after=args.replica_quarantine_after,
        checkpoint_spool=args.checkpoint_spool,
        spool_every=args.spool_every,
        preview_every=args.preview_every,
    )

    import threading

    stopped = threading.Event()

    def _shutdown():
        server.shutdown()  # drains the queue, then stops the listener
        stopped.set()

    stopping = threading.Event()

    def _stop(signum, frame):
        if stopping.is_set():  # second signal: drain is wedged, force quit
            print("[serve] second signal: exiting immediately", flush=True)
            import os

            os._exit(1)
        stopping.set()
        print(f"[serve] signal {signum}: draining queue and shutting down",
              flush=True)
        # shutdown() joins the serve loop; run it off the main thread, which
        # is blocked inside serve_forever
        threading.Thread(target=_shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)

    # parseable readiness line: tests and orchestrators wait for it
    print(f"[serve] listening on http://{args.host}:{server.port} "
          f"(engine={args.engine}, shapes={engine.batch_shapes}, "
          f"max_delay_ms={args.max_delay_ms}, max_queue={args.max_queue})",
          flush=True)
    server.serve_forever()
    stopped.wait(timeout=60)  # let the drain finish before exiting
    print("[serve] shutdown complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
